"""Vectorized Arrow-native transforms for the P training path, plus the
append-only columnar segment store (ISSUE 17).

Reference: the reference's RDD path (SURVEY.md §2.1 "User-facing stores")
keeps event data distributed/columnar from storage scan to trainer input.
Round 1's templates broke that by `.to_pylist()` + per-row ``json.loads``
over every event — a Python loop that walls out long before the ML-25M
north star.  These helpers keep everything in Arrow/numpy kernels:

- ``encode_ids``: dictionary-encode an id column → dense int codes + the
  :class:`BiMap` over *unique* ids (Arrow assigns dictionary codes in
  first-appearance order, matching ``BiMap.string_int`` semantics).
- ``numeric_property``: extract one numeric property from the
  ``properties_json`` column with an Arrow regex kernel — C speed, no
  JSON parse.  Sound for numbers because ``DataMap`` serializes via
  ``json.dumps`` (numbers appear as bare literals); not usable for
  string/nested values, which keep the slow path.
- ``event_mask``: boolean numpy mask for event-name membership.

Segment store (the second half of this module): the event server tees
every landed write into per-(app, channel) append-only ``.seg`` files —
CRC-per-block Arrow IPC payloads, sealed per watermark window via
tmp+rename, merged by a crash-safe compactor — so the PR-10 warm-refresh
delta read becomes a columnar slice whose cost scales with the WINDOW,
not with total store size.  Segments are derived data: the primary event
store stays the source of truth, a reader that cannot prove coverage of
a time range falls back to it, and a crash can at worst shrink coverage
(never corrupt a read — torn tails are truncated at writer open, bad-CRC
blocks stop a reader cold).  The lint (tools/lint_ingest.py) bans raw
``open()`` on ``.seg`` files outside this module so the crash discipline
stays in one place.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
import uuid
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from predictionio_tpu.data.event import BiMap

logger = logging.getLogger(__name__)

__all__ = ["encode_ids", "numeric_property", "bool_property", "event_mask",
           "dict_take", "SegmentStore", "SegmentDiskPressure",
           "filter_event_table", "resolve_segment_root", "SEGMENT_SUFFIX"]

_ColumnLike = Union[pa.Array, pa.ChunkedArray]


def _as_array(col: _ColumnLike) -> pa.Array:
    if isinstance(col, pa.ChunkedArray):
        return col.combine_chunks()
    return col


def dict_take(per_value: np.ndarray, arr: pa.Array, default) -> np.ndarray:
    """Fan a per-DICTIONARY-VALUE result out to per-row via one numpy take.

    The shared core of every dictionary fast path here (and the parquet
    scan filters): null rows surface as null *indices*, which
    ``to_numpy`` converts to float NaN — they must be routed to slot 0
    BEFORE the integer cast and then overwritten with ``default``.
    """
    idx = arr.indices.to_numpy(zero_copy_only=False)
    if arr.null_count:
        nulls = np.asarray(pc.is_null(arr))
        out = per_value[np.where(nulls, 0, idx).astype(np.int64)]
        out[nulls] = default
        return out
    return per_value[idx.astype(np.int64)]


def encode_ids(col: _ColumnLike) -> Tuple[np.ndarray, BiMap]:
    """Id strings → (dense int64 codes, BiMap) without touching Python rows.

    The BiMap is built from the *unique ids present*, in first-appearance
    order (``BiMap.string_int`` semantics), so cost scales with unique
    entities, not events.  Already-dictionary-encoded input (a parquet
    training scan) skips the hash pass entirely: the stored indices are
    re-coded to first-appearance order with two O(events) numpy passes,
    and dictionary entries no surviving row references (a filtered scan
    keeps the full file dictionary) are dropped — the BiMap must not
    invent entities the training data does not contain.
    """
    arr = _as_array(col)
    if arr.null_count:
        raise ValueError(
            f"encode_ids: id column contains {arr.null_count} null(s) — "
            "entity ids must be non-null (filter or fill before encoding)")
    if not pa.types.is_dictionary(arr.type):
        arr = arr.dictionary_encode()
    idx = arr.indices.to_numpy(zero_copy_only=False)
    n_dict = len(arr.dictionary)
    sentinel = np.iinfo(np.int64).max
    first = np.full(n_dict, sentinel, np.int64)
    np.minimum.at(first, idx, np.arange(len(idx), dtype=np.int64))
    present = np.flatnonzero(first < sentinel)
    if len(present) == n_dict and (
            n_dict < 2 or bool(np.all(first[1:] > first[:-1]))):
        # fresh dictionary_encode output: already first-appearance order
        codes = idx.astype(np.int64)
        keys = arr.dictionary.to_pylist()
        return codes, BiMap({k: i for i, k in enumerate(keys)})
    order = present[np.argsort(first[present], kind="stable")]
    remap = np.full(n_dict, -1, np.int64)
    remap[order] = np.arange(len(order))
    codes = remap[idx]
    keys = arr.dictionary.take(pa.array(order)).to_pylist()
    return codes, BiMap({k: i for i, k in enumerate(keys)})


def numeric_property(
    table_or_col: Union[pa.Table, _ColumnLike],
    key: str,
    default: float = 0.0,
) -> np.ndarray:
    """Extract a numeric property per event as float64, ``default`` where
    absent/null.  One Arrow regex kernel over the JSON column."""
    col = (table_or_col.column("properties_json")
           if isinstance(table_or_col, pa.Table) else table_or_col)
    arr = _as_array(col)
    if len(arr) == 0:
        return np.empty(0, dtype=np.float64)
    if pa.types.is_dictionary(arr.type):
        # Low-cardinality property bags (ML-25M has ten distinct rating
        # JSONs across 25M events): run the extraction over the DICTIONARY
        # (O(unique)), then fan out by index — one numpy take.
        if len(arr.dictionary) == 0:
            return np.full(len(arr), default, np.float64)
        return dict_take(numeric_property(arr.dictionary, key,
                                          default=default), arr, default)
    filled = pc.fill_null(arr, "")
    # json.dumps emits numbers bare: "key": -1.5e3, — capture to , } or ].
    pattern = '"' + re.escape(key) + '"\\s*:\\s*(?P<v>-?[0-9][0-9eE+\\-.]*)'
    hit = pc.extract_regex(filled, pattern=pattern)
    vals = pc.struct_field(hit, "v")
    nums = pc.cast(vals, pa.float64())
    out = pc.fill_null(nums, default).to_numpy(zero_copy_only=False).copy()
    # Slow-path guard (round-2 advisor): the regex is only trustworthy when
    # the key text appears EXACTLY once and matched a bare number.  A key
    # repeated inside a nested object / string value, or a numeric value
    # serialized as a string ("rating": "4.5"), falls back to a real JSON
    # parse of just those rows — top-level key only, matching the flat
    # DataMap property-bag semantics.
    lit = '"' + key + '"'
    cnt = pc.count_substring(filled, lit)
    present = pc.greater(cnt, 0)
    # The regex is trusted only when the key text occurs exactly once,
    # matched a bare number, and sits BEFORE any nested object's opening
    # brace — then it provably bound a top-level key.  A flat bag with a
    # trailing nested value ({"rating": 4, "ctx": {...}}) stays on the
    # vectorized path; only key-after-brace rows pay the JSON parse.
    key_off = pc.find_substring(filled, lit)
    brace2 = pc.find_substring(pc.utf8_slice_codeunits(filled, 1), "{")
    nested_before_key = pc.and_(pc.greater_equal(brace2, 0),
                                pc.greater(key_off, brace2))  # off-by-1 safe
    ambiguous = pc.and_(present,
                        pc.or_(pc.or_(pc.greater(cnt, 1), pc.is_null(nums)),
                               nested_before_key))
    amb_idx = np.flatnonzero(ambiguous.to_numpy(zero_copy_only=False))
    if len(amb_idx):
        import json as _json

        raw = filled.take(pa.array(amb_idx)).to_pylist()
        for i, s in zip(amb_idx, raw):
            try:
                v = _json.loads(s).get(key, default)
                out[i] = float(v) if not isinstance(v, bool) else default
            except (ValueError, TypeError, AttributeError):
                out[i] = default
    return out


def bool_property(
    table_or_col: Union[pa.Table, _ColumnLike],
    key: str,
) -> np.ndarray:
    """True where property ``key`` is JSON ``true`` or ``1`` — one regex
    kernel (json.dumps emits booleans as bare ``true``/``false``)."""
    col = (table_or_col.column("properties_json")
           if isinstance(table_or_col, pa.Table) else table_or_col)
    arr = _as_array(col)
    if len(arr) == 0:
        return np.empty(0, dtype=bool)
    if pa.types.is_dictionary(arr.type):
        if len(arr.dictionary) == 0:
            return np.zeros(len(arr), bool)
        return dict_take(bool_property(arr.dictionary, key), arr, False)
    pattern = '"' + re.escape(key) + '"\\s*:\\s*(true|1(?:\\.0*)?)([,}\\s]|$)'
    return pc.match_substring_regex(
        pc.fill_null(arr, ""), pattern
    ).to_numpy(zero_copy_only=False)


def event_mask(
    table: pa.Table,
    names: Sequence[str],
    column: str = "event",
) -> np.ndarray:
    """Boolean mask of rows whose event name is in ``names``."""
    arr = _as_array(table.column(column))
    if pa.types.is_dictionary(arr.type) and len(arr.dictionary):
        # O(unique event names) membership + one numpy take
        vm = pc.is_in(arr.dictionary, value_set=pa.array(list(names)))
        return dict_take(vm.to_numpy(zero_copy_only=False), arr, False)
    return pc.is_in(
        arr, value_set=pa.array(list(names))
    ).to_numpy(zero_copy_only=False)


# ===========================================================================
# Columnar segment store (ISSUE 17 tentpole)
# ===========================================================================
#
# On-disk layout (single writer per root — the event server; readers are
# lock-free and cross-process safe):
#
#     <root>/app_<id>/<default|ch_N>/
#         manifest.json            # THE commit point (tmp+rename+dir fsync)
#         seg-<seq>-<wStart>-<wEnd>.seg   # sealed, fsynced, immutable
#         active-<wStart>-<rand>.tmp      # open window, never claimed
#
# Segment file = 6-byte magic + blocks of [u32 len][payload][u32 crc32],
# payload = one Arrow IPC stream of EVENT_ARROW_SCHEMA rows.  Sealed files
# are fsynced before the rename, so a bad CRC there is real corruption
# (reader drops coverage, falls back to the primary store).  The active
# file is deliberately NOT fsynced per block — segments are derived data —
# so a crash can tear its tail; recovery truncates to the last valid
# block (counted + WARNed, the PR-2 journal discipline) and then discards
# the file: its window was never claimed, the primary store has the rows.
#
# Coverage is one interval per (app, channel): [floorUs, activeStartUs).
# The claim: every event the primary store holds with event_time_us in
# that interval is present in the sealed segments.  Seal picks the window
# end ``now - grace`` so rows still in flight between primary commit and
# segment tee can't be claimed before they land; a genuinely LATE event
# (client-stamped event_time older than the open window) would silently
# break the claim, so it ratchets ``floor`` up to the window start —
# coverage shrinks, reads fall back, correctness holds.  Reads overlap
# segments by their actual data range (minUs/maxUs), not their window
# label, so straggler rows teed into the next window are still found.
# ===========================================================================

from predictionio_tpu.resilience.faults import fault_point

SEGMENT_SUFFIX = ".seg"
_SEG_MAGIC = b"PSEG1\n"
_U32 = 4


class SegmentDiskPressure(RuntimeError):
    """Free disk below PIO_DISK_MIN_FREE_BYTES — the segment writer backs
    off BEFORE ENOSPC can tear a write; ingest itself continues (segments
    are derived data) with /ready reporting the degradation."""


def resolve_segment_root(explicit: Optional[str] = None) -> Optional[Path]:
    """Segment root: explicit arg > $PIO_SEGMENT_DIR > $PIO_HOME/segments.
    ``PIO_SEGMENTS=off`` disables segments entirely (returns None)."""
    if os.environ.get("PIO_SEGMENTS", "").lower() in ("off", "0", "false"):
        return None
    if explicit:
        return Path(explicit)
    env = os.environ.get("PIO_SEGMENT_DIR")
    if env:
        return Path(env)
    home = os.environ.get("PIO_HOME")
    if home:
        return Path(home) / "segments"
    return None


def _env_f(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("bad %s=%r; using %s", name, raw, default)
        return default


def _now_us(clock) -> int:
    return int(clock() * 1e6)


def recover_segment_tail(path: Path, truncate: bool = True) -> Dict[str, Any]:
    """Torn-tail recovery for one segment file — the PR-2 journal
    discipline: scan ``[len][payload][crc]`` blocks, stop at the first
    short read or CRC mismatch, truncate the file to the last valid
    block, and report what happened.

    Returns ``{"rows", "blocks", "valid_bytes", "torn_bytes",
    "payloads"}`` (payloads as raw bytes, CRC-verified).  Never raises on
    damage — damage is the expected input.
    """
    payloads: List[bytes] = []
    rows = 0
    size = path.stat().st_size
    with open(path, "r+b" if truncate else "rb") as f:
        magic = f.read(len(_SEG_MAGIC))
        if magic != _SEG_MAGIC:
            valid = 0
        else:
            valid = len(_SEG_MAGIC)
            while True:
                head = f.read(_U32)
                if len(head) < _U32:
                    break
                ln = int.from_bytes(head, "little")
                body = f.read(ln + _U32)
                if len(body) < ln + _U32:
                    break
                payload, crc = body[:ln], body[ln:]
                if zlib.crc32(payload) != int.from_bytes(crc, "little"):
                    break
                payloads.append(payload)
                valid += _U32 + ln + _U32
        torn = size - valid
        if torn and truncate:
            f.truncate(valid)
    for p in payloads:
        with pa.ipc.open_stream(p) as rd:
            rows += rd.read_all().num_rows
    if torn:
        logger.warning(
            "segment %s: torn tail — truncated %d byte(s) to last valid "
            "block (%d block(s), %d row(s) kept)",
            path, torn, len(payloads), rows)
        _seg_counter("pio_segment_torn_bytes_total", torn)
    return {"rows": rows, "blocks": len(payloads), "valid_bytes": valid,
            "torn_bytes": torn, "payloads": payloads}


def _payloads_to_table(payloads: Sequence[bytes]) -> pa.Table:
    from predictionio_tpu.data.storage.base import EVENT_ARROW_SCHEMA

    tables = []
    for p in payloads:
        with pa.ipc.open_stream(p) as rd:
            tables.append(rd.read_all())
    if not tables:
        return pa.table(
            {f.name: pa.nulls(0, f.type) for f in EVENT_ARROW_SCHEMA},
            schema=EVENT_ARROW_SCHEMA)
    return pa.concat_tables(tables, promote_options="permissive")


def _table_to_payload(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as wr:
        wr.write_table(table)
    return sink.getvalue().to_pybytes()


def _seg_counter(name: str, by: int = 1) -> None:
    try:
        from predictionio_tpu.obs import get_registry

        reg = get_registry()
        # get-or-create with literal names (metrics-lint schema check)
        counters = {
            "pio_segment_torn_bytes_total": reg.counter(
                "pio_segment_torn_bytes_total",
                "Bytes truncated from torn segment tails on recovery."),
            "pio_segment_active_discarded_total": reg.counter(
                "pio_segment_active_discarded_total",
                "Crashed unsealed windows discarded on reopen."),
            "pio_segment_late_events_total": reg.counter(
                "pio_segment_late_events_total",
                "Events below the open window start (floor ratcheted)."),
            "pio_segment_seals_total": reg.counter(
                "pio_segment_seals_total",
                "Segment windows sealed (manifest commits)."),
            "pio_segment_compactions_total": reg.counter(
                "pio_segment_compactions_total",
                "Small-segment compaction runs committed."),
        }
        counters[name].inc(by)
    except Exception:  # metrics must never break the data plane
        pass


def filter_event_table(
    table: pa.Table,
    start_us: Optional[int] = None,
    until_us: Optional[int] = None,
    entity_type: Optional[str] = None,
    entity_id: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
) -> pa.Table:
    """Apply the ``find_columnar`` filter set to an in-memory event table
    (segment reads return raw window slices; this brings them to parity
    with what a storage backend's filtered scan would have returned)."""
    if table.num_rows == 0:
        return table
    mask = np.ones(table.num_rows, dtype=bool)
    if start_us is not None or until_us is not None:
        ts = _as_array(table.column("event_time_us")).to_numpy(
            zero_copy_only=False)
        if start_us is not None:
            mask &= ts >= start_us
        if until_us is not None:
            mask &= ts < until_us
    for col, want in (("entity_type", entity_type),
                      ("entity_id", entity_id),
                      ("target_entity_type", target_entity_type),
                      ("target_entity_id", target_entity_id)):
        if want is not None:
            mask &= pc.equal(
                pc.fill_null(_as_array(table.column(col)), ""), want
            ).to_numpy(zero_copy_only=False)
    if event_names:
        mask &= event_mask(table, event_names)
    if bool(mask.all()):
        return table
    return table.filter(pa.array(mask))


class _SegmentDir:
    """Writer-side state for one (app, channel) segment directory."""

    def __init__(self, path: Path, clock):
        self.path = path
        self.lock = threading.Lock()
        self.clock = clock
        self.active_file = None  # open file handle for the active window
        self.active_path: Optional[Path] = None
        self.active_rows = 0
        self.active_bytes = 0
        self.active_min_us: Optional[int] = None
        self.active_max_us: Optional[int] = None
        self.active_opened_s = 0.0
        self.manifest = self._load_and_recover()

    # -- manifest -----------------------------------------------------------

    def _load_and_recover(self) -> Dict[str, Any]:
        self.path.mkdir(parents=True, exist_ok=True)
        mpath = self.path / "manifest.json"
        if mpath.exists():
            manifest = json.loads(mpath.read_text())
        else:
            now = _now_us(self.clock)
            manifest = {"version": 1, "floorUs": now, "nextSeq": 0,
                        "activeStartUs": now, "segments": []}
        # Crash recovery (single writer): anything on disk the manifest
        # does not reference is garbage from an interrupted seal/compact —
        # EXCEPT a leftover active file, which gets the torn-tail
        # treatment first so the damage is measured and logged, then is
        # discarded: its window was never claimed, the primary store is
        # authoritative for those rows, and keeping it would let a future
        # seal claim a window with rows lost from the in-flight tee.
        referenced = {e["file"] for e in manifest["segments"]}
        for p in sorted(self.path.iterdir()):
            if p.name == "manifest.json" or p.name in referenced:
                continue
            if p.name.startswith("active-") and p.suffix == ".tmp":
                try:
                    stats = recover_segment_tail(p)
                    logger.warning(
                        "segment dir %s: discarding crashed active window "
                        "(%d recoverable row(s); primary store is "
                        "authoritative, window was never claimed)",
                        self.path, stats["rows"])
                    _seg_counter("pio_segment_active_discarded_total")
                except OSError:
                    pass
            elif p.suffix not in (SEGMENT_SUFFIX, ".tmp"):
                continue  # not ours — leave unknown files alone
            else:
                logger.warning("segment dir %s: sweeping orphan %s "
                               "(interrupted seal/compaction)",
                               self.path, p.name)
            try:
                p.unlink()
            except OSError:
                pass
        return manifest

    def write_manifest(self) -> None:
        fault_point("segment.manifest")
        tmp = self.path / "manifest.tmp"
        data = json.dumps(self.manifest, indent=0).encode()
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, self.path / "manifest.json")
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    # -- active window ------------------------------------------------------

    def append_table(self, table: pa.Table) -> None:
        """One CRC block per tee (no cross-call buffering: a crash may
        tear only the LAST write, never lose earlier acknowledged ones)."""
        if table.num_rows == 0:
            return
        fault_point("segment.append")
        ts = _as_array(table.column("event_time_us")).to_numpy(
            zero_copy_only=False)
        tmin, tmax = int(ts.min()), int(ts.max())
        if tmin < self.manifest["activeStartUs"]:
            # A late event: older than the open window, i.e. inside (or
            # below) ranges sealed segments claim complete coverage of.
            # Keeping the claim would be a silent lie — ratchet the floor
            # to the window start instead; reads below it fall back to
            # the primary store, which has the row.
            if self.manifest["floorUs"] < self.manifest["activeStartUs"]:
                logger.warning(
                    "segment dir %s: late event (event_time %dus < window "
                    "start %dus) — raising coverage floor; delta reads "
                    "below it fall back to the primary store",
                    self.path, tmin, self.manifest["activeStartUs"])
                self.manifest["floorUs"] = self.manifest["activeStartUs"]
                self.write_manifest()
            _seg_counter("pio_segment_late_events_total", table.num_rows)
        if self.active_file is None:
            start = self.manifest["activeStartUs"]
            self.active_path = self.path / (
                f"active-{start}-{uuid.uuid4().hex[:8]}.tmp")
            self.active_file = open(self.active_path, "wb")
            self.active_file.write(_SEG_MAGIC)
            self.active_bytes = len(_SEG_MAGIC)
            self.active_opened_s = self.clock()
        payload = _table_to_payload(table)
        block = (len(payload).to_bytes(_U32, "little") + payload
                 + zlib.crc32(payload).to_bytes(_U32, "little"))
        self.active_file.write(block)
        self.active_bytes += len(block)
        self.active_rows += table.num_rows
        self.active_min_us = (tmin if self.active_min_us is None
                              else min(self.active_min_us, tmin))
        self.active_max_us = (tmax if self.active_max_us is None
                              else max(self.active_max_us, tmax))

    def seal(self, grace_us: int) -> Optional[Dict[str, Any]]:
        """Seal the active window: fsync, rename to its final ``.seg``
        name, commit to the manifest.  Window end is ``now - grace`` so
        rows still in flight between primary commit and segment tee
        cannot fall inside a claimed range."""
        if self.active_file is None or self.active_rows == 0:
            if self.active_file is not None:
                self.active_file.close()
                try:
                    self.active_path.unlink()
                except OSError:
                    pass
                self.active_file = None
                self.active_path = None
            return None
        fault_point("segment.seal")
        self.active_file.flush()
        os.fsync(self.active_file.fileno())
        self.active_file.close()
        w_start = self.manifest["activeStartUs"]
        w_end = max(w_start + 1, _now_us(self.clock) - grace_us)
        seq = self.manifest["nextSeq"]
        final = self.path / f"seg-{seq:08d}-{w_start}-{w_end}{SEGMENT_SUFFIX}"
        os.rename(self.active_path, final)
        entry = {"file": final.name, "wStartUs": w_start, "wEndUs": w_end,
                 "minUs": self.active_min_us, "maxUs": self.active_max_us,
                 "rows": self.active_rows, "bytes": self.active_bytes}
        self.manifest["segments"].append(entry)
        self.manifest["nextSeq"] = seq + 1
        self.manifest["activeStartUs"] = w_end
        self.write_manifest()  # fsyncs the dir → covers the rename too
        self.active_file = None
        self.active_path = None
        self.active_rows = 0
        self.active_bytes = 0
        self.active_min_us = None
        self.active_max_us = None
        _seg_counter("pio_segment_seals_total")
        return entry

    # -- compaction ---------------------------------------------------------

    def compact(self, small_bytes: int) -> Dict[str, int]:
        """Merge maximal runs of adjacent small sealed segments.

        Crash-safe by construction: the merged file is written aside and
        fsynced, then the manifest rename commits the swap, then the old
        files are unlinked.  A kill at ANY point leaves either the old
        set (manifest not yet renamed — the merged tmp is swept at next
        open) or the new set (manifest renamed — leftover old files are
        swept at next open) fully readable.  Never both (the manifest
        references exactly one set), never neither.
        """
        segs = self.manifest["segments"]
        runs: List[Tuple[int, int]] = []
        i = 0
        while i < len(segs):
            j = i
            while j < len(segs) and segs[j]["bytes"] < small_bytes:
                j += 1
            if j - i >= 2:
                runs.append((i, j))
            i = max(j, i + 1)
        stats = {"runs": 0, "segments_in": 0, "segments_out": 0}
        for start, end in reversed(runs):  # right-to-left: indices stable
            run = segs[start:end]
            fault_point("segment.compact")
            tables = []
            for e in run:
                rec = recover_segment_tail(self.path / e["file"],
                                           truncate=False)
                if rec["torn_bytes"] or rec["rows"] != e["rows"]:
                    logger.error(
                        "segment %s: sealed file damaged (%d torn bytes, "
                        "%d/%d rows) — skipping compaction of this run",
                        e["file"], rec["torn_bytes"], rec["rows"], e["rows"])
                    tables = None
                    break
                tables.append(_payloads_to_table(rec["payloads"]))
            if tables is None:
                continue
            merged = pa.concat_tables(tables, promote_options="permissive")
            payload = _table_to_payload(merged)
            block = (len(payload).to_bytes(_U32, "little") + payload
                     + zlib.crc32(payload).to_bytes(_U32, "little"))
            seq = self.manifest["nextSeq"]
            w_start, w_end = run[0]["wStartUs"], run[-1]["wEndUs"]
            final = self.path / (
                f"seg-{seq:08d}-{w_start}-{w_end}{SEGMENT_SUFFIX}")
            tmp = self.path / f"compact-{uuid.uuid4().hex[:8]}.tmp"
            with open(tmp, "wb") as f:
                f.write(_SEG_MAGIC + block)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, final)
            fault_point("segment.compact.commit")
            entry = {"file": final.name, "wStartUs": w_start,
                     "wEndUs": w_end,
                     "minUs": min(e["minUs"] for e in run),
                     "maxUs": max(e["maxUs"] for e in run),
                     "rows": merged.num_rows,
                     "bytes": len(_SEG_MAGIC) + len(block)}
            self.manifest["segments"][start:end] = [entry]
            self.manifest["nextSeq"] = seq + 1
            self.write_manifest()  # ← the commit point
            fault_point("segment.compact.cleanup")
            for e in run:
                try:
                    (self.path / e["file"]).unlink()
                except OSError:
                    pass
            stats["runs"] += 1
            stats["segments_in"] += len(run)
            stats["segments_out"] += 1
            _seg_counter("pio_segment_compactions_total")
        return stats


class SegmentStore:
    """Per-(app, channel) append-only columnar segment files.

    Single WRITER per root (the event server tees landed writes through
    :meth:`append_events`); any number of cross-process READERS go
    through :meth:`read_window`, which consults only ``manifest.json``
    and sealed files.  See the module banner for the crash model.
    """

    def __init__(self, root, *, roll_bytes: Optional[int] = None,
                 roll_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 min_free_bytes: Optional[int] = None,
                 compact_small_bytes: Optional[int] = None,
                 compact_trigger: Optional[int] = None,
                 clock=time.time):
        self.root = Path(root)
        self.roll_bytes = int(roll_bytes if roll_bytes is not None
                              else _env_f("PIO_SEGMENT_ROLL_BYTES", 4 << 20))
        self.roll_s = float(roll_s if roll_s is not None
                            else _env_f("PIO_SEGMENT_ROLL_S", 60.0))
        self.grace_us = int(1e6 * (grace_s if grace_s is not None
                                   else _env_f("PIO_SEGMENT_GRACE_S", 5.0)))
        self.min_free_bytes = int(
            min_free_bytes if min_free_bytes is not None
            else _env_f("PIO_DISK_MIN_FREE_BYTES", 0))
        self.compact_small_bytes = int(
            compact_small_bytes if compact_small_bytes is not None
            else _env_f("PIO_SEGMENT_COMPACT_BYTES", 1 << 20))
        self.compact_trigger = int(
            compact_trigger if compact_trigger is not None
            else _env_f("PIO_SEGMENT_COMPACT_TRIGGER", 16))
        self.clock = clock
        self._dirs: Dict[Tuple[int, Optional[int]], _SegmentDir] = {}
        self._dirs_lock = threading.Lock()
        self._disk_checked_s = 0.0
        self._disk_free = None

    @classmethod
    def open_default(cls, **kwargs) -> Optional["SegmentStore"]:
        root = resolve_segment_root()
        return cls(root, **kwargs) if root is not None else None

    # -- plumbing -----------------------------------------------------------

    @staticmethod
    def _dir_name(app_id: int, channel_id: Optional[int]) -> str:
        ch = "default" if channel_id is None else f"ch_{channel_id}"
        return f"app_{app_id}/{ch}"

    def _dir(self, app_id: int, channel_id: Optional[int]) -> _SegmentDir:
        key = (app_id, channel_id)
        with self._dirs_lock:
            d = self._dirs.get(key)
            if d is None:
                d = _SegmentDir(self.root / self._dir_name(app_id,
                                                           channel_id),
                                self.clock)
                self._dirs[key] = d
            return d

    def disk_pressure(self) -> bool:
        """True when free space under the root is below the configured
        floor (~1s cached — this runs on every tee)."""
        if self.min_free_bytes <= 0:
            return False
        now = self.clock()
        if now - self._disk_checked_s > 1.0 or self._disk_free is None:
            self._disk_checked_s = now
            try:
                self.root.mkdir(parents=True, exist_ok=True)
                self._disk_free = shutil.disk_usage(self.root).free
            except OSError:
                self._disk_free = 0
        return self._disk_free < self.min_free_bytes

    # -- write path ---------------------------------------------------------

    def append_events(self, app_id: int, channel_id: Optional[int],
                      events) -> None:
        """Tee one landed batch into the active window (raises
        :class:`SegmentDiskPressure` instead of risking a torn ENOSPC
        write; any other failure is the caller's to contain — ingest
        must never fail because a derived file could not be written)."""
        from predictionio_tpu.data.storage.base import events_to_arrow

        self.append_table(app_id, channel_id, events_to_arrow(events))

    def append_table(self, app_id: int, channel_id: Optional[int],
                     table: pa.Table) -> None:
        if table.num_rows == 0:
            return
        if self.disk_pressure():
            raise SegmentDiskPressure(
                f"free disk under {self.root} below "
                f"PIO_DISK_MIN_FREE_BYTES={self.min_free_bytes}")
        d = self._dir(app_id, channel_id)
        with d.lock:
            d.append_table(table)
            if (d.active_bytes >= self.roll_bytes
                    or self.clock() - d.active_opened_s >= self.roll_s):
                d.seal(self.grace_us)
                self._maybe_compact(d)

    def seal_all(self) -> int:
        """Seal every open window (server drain/stop, bench barriers)."""
        sealed = 0
        with self._dirs_lock:
            dirs = list(self._dirs.values())
        for d in dirs:
            with d.lock:
                if d.seal(self.grace_us) is not None:
                    sealed += 1
                    self._maybe_compact(d)
        return sealed

    def _maybe_compact(self, d: _SegmentDir) -> None:
        if self.compact_trigger <= 0:
            return
        small = sum(1 for e in d.manifest["segments"]
                    if e["bytes"] < self.compact_small_bytes)
        if small >= self.compact_trigger:
            d.compact(self.compact_small_bytes)

    def compact(self, app_id: int,
                channel_id: Optional[int] = None) -> Dict[str, int]:
        d = self._dir(app_id, channel_id)
        with d.lock:
            return d.compact(self.compact_small_bytes)

    # -- read path (cross-process safe: manifest + sealed files only) -------

    def read_window(
        self, app_id: int, channel_id: Optional[int],
        start_us: int, until_us: int, **filters
    ) -> Optional[Tuple[pa.Table, int]]:
        """Columnar slice of ``[start_us, min(until_us, covered))``.

        Returns ``(table, covered_until_us)`` — the caller reads the
        remaining ``[covered_until_us, until_us)`` tail from the primary
        store — or None when segments cannot prove coverage from
        ``start_us`` (reader falls back entirely; never guesses).
        """
        mpath = (self.root / self._dir_name(app_id, channel_id)
                 / "manifest.json")
        try:
            manifest = json.loads(mpath.read_text())
        except (OSError, ValueError):
            return None
        floor = manifest.get("floorUs", 0)
        covered = manifest.get("activeStartUs", floor)
        if start_us < floor:
            return None  # claim does not reach back that far
        covered_until = min(until_us, covered)
        if covered_until <= start_us:
            return None  # nothing useful covered — pure fallback
        tables: List[pa.Table] = []
        for e in manifest.get("segments", []):
            lo = min(e["wStartUs"], e["minUs"])
            hi = max(e["wEndUs"], e["maxUs"] + 1)
            if hi <= start_us or lo >= covered_until:
                continue
            rec = recover_segment_tail(self.root
                                       / self._dir_name(app_id, channel_id)
                                       / e["file"], truncate=False)
            if rec["torn_bytes"] or rec["rows"] != e["rows"]:
                logger.error(
                    "segment %s damaged (%d torn bytes, %d/%d rows) — "
                    "dropping segment coverage, falling back to primary "
                    "store", e["file"], rec["torn_bytes"], rec["rows"],
                    e["rows"])
                return None
            tables.append(_payloads_to_table(rec["payloads"]))
        if tables:
            table = pa.concat_tables(tables, promote_options="permissive")
        else:
            table = _payloads_to_table(())
        table = filter_event_table(table, start_us=start_us,
                                   until_us=covered_until, **filters)
        return table, covered_until

    # -- observability ------------------------------------------------------

    def status(self) -> List[Dict[str, Any]]:
        """One row per (app, channel) dir on disk — for /ready and
        ``pio status`` (reads manifests; safe cross-process)."""
        out: List[Dict[str, Any]] = []
        if not self.root.exists():
            return out
        for mpath in sorted(self.root.glob("app_*/*/manifest.json")):
            try:
                manifest = json.loads(mpath.read_text())
            except (OSError, ValueError):
                continue
            segs = manifest.get("segments", [])
            out.append({
                "dir": str(mpath.parent.relative_to(self.root)),
                "segments": len(segs),
                "rows": sum(e["rows"] for e in segs),
                "bytes": sum(e["bytes"] for e in segs),
                "floorUs": manifest.get("floorUs", 0),
                "coveredUntilUs": manifest.get("activeStartUs", 0),
            })
        return out

    def close(self) -> None:
        self.seal_all()
