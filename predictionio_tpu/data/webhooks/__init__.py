"""Webhook connectors — adapt third-party payloads to PIO events.

Reference: data/src/main/scala/org/apache/predictionio/data/webhooks/
(SURVEY.md §2.1): ``JsonConnector`` / ``FormConnector`` traits +
``ConnectorUtil`` dispatch, with example connectors for segment.io
(JSON) and mailchimp (form-encoded).  The event server mounts them at
``POST /webhooks/<connector>.json`` (JSON) and
``POST /webhooks/<connector>`` (form).
"""

from predictionio_tpu.data.webhooks.connectors import (
    ConnectorError,
    FormConnector,
    JsonConnector,
    MailchimpConnector,
    SegmentIOConnector,
    get_connector,
    register_connector,
)

__all__ = [
    "ConnectorError",
    "FormConnector",
    "JsonConnector",
    "MailchimpConnector",
    "SegmentIOConnector",
    "get_connector",
    "register_connector",
]
