"""Connector traits + the two reference example connectors.

Reference classes: webhooks/JsonConnector.scala, FormConnector.scala,
ConnectorUtil.scala, segmentio/SegmentIOConnector.scala,
mailchimp/MailChimpConnector.scala (SURVEY.md §2.1 "Webhooks").
A connector maps one provider payload to the standard event JSON
(Appendix A), which then flows through the normal ingestion path —
connectors never write storage themselves.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Mapping, Type

__all__ = ["ConnectorError", "JsonConnector", "FormConnector",
           "SegmentIOConnector", "MailchimpConnector", "register_connector",
           "get_connector"]


class ConnectorError(ValueError):
    """Reference: ConnectorException."""


class JsonConnector(abc.ABC):
    """Payload is a JSON object (reference: JsonConnector.toEventJson)."""

    @abc.abstractmethod
    def to_event_json(self, payload: Mapping[str, Any]) -> Dict[str, Any]: ...

    def to_events_json(self, payload: Mapping[str, Any]) -> List[Dict[str, Any]]:
        """Burst entry (ISSUE 17): one provider delivery → N event JSONs,
        fed through the server's batched-ingest fold as ONE group commit
        instead of a per-row ``create_event`` loop.  Default wraps the
        single-event mapping; connectors whose providers batch deliveries
        (segment.io) override."""
        return [self.to_event_json(payload)]


class FormConnector(abc.ABC):
    """Payload is form-encoded key/value (reference: FormConnector)."""

    @abc.abstractmethod
    def to_event_json(self, form: Mapping[str, str]) -> Dict[str, Any]: ...

    def to_events_json(self, form: Mapping[str, str]) -> List[Dict[str, Any]]:
        """Burst entry — see :meth:`JsonConnector.to_events_json`."""
        return [self.to_event_json(form)]


class SegmentIOConnector(JsonConnector):
    """Reference: segmentio/SegmentIOConnector — maps track/identify/...

    Segment spec fields: type, userId/anonymousId, event, properties/traits,
    timestamp.
    """

    def to_events_json(self, payload: Mapping[str, Any]) -> List[Dict[str, Any]]:
        """Segment's HTTP API delivers call batches as
        ``{"batch": [msg, ...]}`` — coalesce the whole delivery into one
        event list (one group commit downstream).  A malformed message
        inside the batch stays a per-item error: it is passed through as
        a ConnectorError placeholder for the fold to answer 400."""
        if isinstance(payload, Mapping) and isinstance(
                payload.get("batch"), list):
            out: List[Any] = []
            for msg in payload["batch"]:
                try:
                    out.append(self.to_event_json(msg))
                except ConnectorError as e:
                    out.append(e)
            return out
        return [self.to_event_json(payload)]

    def to_event_json(self, payload: Mapping[str, Any]) -> Dict[str, Any]:
        typ = payload.get("type")
        if not typ:
            raise ConnectorError("segmentio payload missing 'type'.")
        user = payload.get("userId") or payload.get("anonymousId")
        if not user:
            raise ConnectorError("segmentio payload missing userId/anonymousId.")
        common: Dict[str, Any] = {
            "entityType": "user",
            "entityId": str(user),
        }
        ts = payload.get("timestamp")
        if ts:
            common["eventTime"] = ts
        if typ == "track":
            name = payload.get("event")
            if not name:
                raise ConnectorError("segmentio track missing 'event'.")
            return {**common, "event": name,
                    "properties": dict(payload.get("properties") or {})}
        if typ == "identify":
            return {**common, "event": "$set",
                    "properties": dict(payload.get("traits") or {})}
        if typ in ("page", "screen"):
            props = dict(payload.get("properties") or {})
            if payload.get("name"):
                props["name"] = payload["name"]
            return {**common, "event": typ, "properties": props}
        if typ == "alias":
            return {**common, "event": "alias",
                    "properties": {"previousId": payload.get("previousId")}}
        if typ == "group":
            return {**common, "event": "group",
                    "properties": {"groupId": payload.get("groupId"),
                                   **dict(payload.get("traits") or {})}}
        raise ConnectorError(f"segmentio type {typ!r} not supported.")


class MailchimpConnector(FormConnector):
    """Reference: mailchimp/MailChimpConnector — subscribe/unsubscribe/...

    Mailchimp webhooks POST form fields like ``type=subscribe``,
    ``data[email]=...``, ``fired_at=...``.
    """

    _SUPPORTED = ("subscribe", "unsubscribe", "profile", "upemail",
                  "cleaned", "campaign")

    def to_event_json(self, form: Mapping[str, str]) -> Dict[str, Any]:
        typ = form.get("type")
        if typ not in self._SUPPORTED:
            raise ConnectorError(f"mailchimp type {typ!r} not supported.")
        entity = (form.get("data[email]") or form.get("data[new_email]")
                  or form.get("data[id]"))
        if not entity:
            raise ConnectorError("mailchimp payload missing data[email]/data[id].")
        props = {k[5:-1]: v for k, v in form.items()
                 if k.startswith("data[") and k.endswith("]")}
        out = {
            "event": typ,
            "entityType": "user",
            "entityId": str(entity),
            "properties": props,
        }
        fired = form.get("fired_at")
        if fired:
            # Mailchimp sends "YYYY-MM-DD HH:MM:SS" — ISO-ify.
            out["eventTime"] = fired.replace(" ", "T") + "+00:00" \
                if "T" not in fired and "+" not in fired else fired
        return out


_REGISTRY: Dict[str, Any] = {
    "segmentio": SegmentIOConnector(),
    "mailchimp": MailchimpConnector(),
}


def register_connector(name: str, connector) -> None:
    """Plugin hook (reference: connector discovery via ServiceLoader)."""
    _REGISTRY[name] = connector


def get_connector(name: str):
    c = _REGISTRY.get(name)
    if c is None:
        raise ConnectorError(f"Unknown webhook connector {name!r}; "
                             f"registered: {sorted(_REGISTRY)}")
    return c
