"""Layered infrastructure configuration.

Reference: conf/pio-env.sh.template + data/.../data/storage/Storage.scala's
``StorageClientConfig`` env parsing.  The reference reads::

    PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}
    PIO_STORAGE_SOURCES_<SOURCE>_{TYPE,HOSTS,PORTS,PATH,...}

We keep exactly that env contract (layer (a) of the reference's config system,
SURVEY.md §5.6), add an optional TOML file (``$PIO_HOME/pio-env.toml`` or
``$PIO_CONFIG_FILE``) as the shell-template analogue, and default to fully
local backends so a fresh checkout works with zero configuration:

- METADATA  → sqlite   at ``$PIO_HOME/storage/pio.db``
- EVENTDATA → sqlite   at ``$PIO_HOME/storage/pio.db``  (events + metadata can
  share a db file; the parquet event-log source is available for batch-heavy
  apps via ``PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=PARQUET``)
- MODELDATA → localfs  at ``$PIO_HOME/storage/models``
"""

from __future__ import annotations

import os

try:  # stdlib on 3.11+; gate so 3.10 installs work (TOML files optional)
    import tomllib
except ImportError:  # pragma: no cover - interpreter-version dependent
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:
        tomllib = None  # type: ignore[assignment]
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Mapping, Optional

__all__ = ["StorageSourceConfig", "RepositoryConfig", "PioConfig",
           "load_config", "pio_home", "env_bool"]

_REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")


def env_bool(raw: Optional[str], default: bool) -> bool:
    """THE boolean env-var dialect (``PIO_BATCH_ENABLED``,
    ``PIO_RETAIN_PREVIOUS``, ...): unset/empty → ``default``; otherwise
    anything but ``0/off/false/no`` (case-insensitive) is true."""
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in ("0", "off", "false", "no")


def pio_home(env: Optional[Mapping[str, str]] = None) -> Path:
    env = env if env is not None else os.environ
    home = env.get("PIO_HOME")
    if home:
        return Path(home)
    return Path(env.get("HOME", "/tmp")) / ".predictionio_tpu"


@dataclass(frozen=True)
class StorageSourceConfig:
    """One named storage source (reference: StorageClientConfig)."""

    name: str
    type: str                      # sqlite | parquetlog | localfs | memory
    properties: Dict[str, str] = field(default_factory=dict)

    @property
    def path(self) -> Optional[str]:
        return self.properties.get("PATH")


@dataclass(frozen=True)
class RepositoryConfig:
    """Binding of a logical repository to a source (reference: repositories map)."""

    repo: str                      # METADATA | EVENTDATA | MODELDATA
    namespace: str                 # table/keyspace prefix (reference: _NAME)
    source: str                    # source name (reference: _SOURCE)


@dataclass(frozen=True)
class PioConfig:
    home: Path
    sources: Dict[str, StorageSourceConfig]
    repositories: Dict[str, RepositoryConfig]
    extra: Dict[str, str] = field(default_factory=dict)

    def source_for(self, repo: str) -> StorageSourceConfig:
        rc = self.repositories[repo.upper()]
        try:
            return self.sources[rc.source]
        except KeyError:
            raise KeyError(
                f"Repository {repo} points at undefined storage source "
                f"{rc.source!r}; defined sources: {sorted(self.sources)}"
            ) from None


def _defaults(home: Path) -> Dict[str, str]:
    storage = home / "storage"
    return {
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "pio_meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQLITE",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "pio_event",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQLITE",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "pio_model",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "LOCALFS",
        "PIO_STORAGE_SOURCES_SQLITE_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQLITE_PATH": str(storage / "pio.db"),
        "PIO_STORAGE_SOURCES_PARQUET_TYPE": "parquetlog",
        "PIO_STORAGE_SOURCES_PARQUET_PATH": str(storage / "events"),
        "PIO_STORAGE_SOURCES_LOCALFS_TYPE": "localfs",
        "PIO_STORAGE_SOURCES_LOCALFS_PATH": str(storage / "models"),
        "PIO_STORAGE_SOURCES_MEMORY_TYPE": "memory",
    }


def _load_toml(path: Path) -> Dict[str, str]:
    """Flatten a TOML file into PIO_* env-style keys.

    Either literal env keys under ``[env]`` or structured::

        [storage.repositories.eventdata]
        name = "pio_event"
        source = "PARQUET"
        [storage.sources.PARQUET]
        type = "parquetlog"
        path = "/data/events"
    """
    if tomllib is None:
        raise RuntimeError(
            f"cannot read {path}: no TOML parser on this interpreter "
            "(tomllib needs Python 3.11+, or install tomli); "
            "use PIO_STORAGE_* env configuration instead.")
    with open(path, "rb") as f:
        doc = tomllib.load(f)
    flat: Dict[str, str] = {}
    for k, v in (doc.get("env") or {}).items():
        flat[str(k)] = str(v)
    storage = doc.get("storage") or {}
    for repo, spec in (storage.get("repositories") or {}).items():
        up = repo.upper()
        if "name" in spec:
            flat[f"PIO_STORAGE_REPOSITORIES_{up}_NAME"] = str(spec["name"])
        if "source" in spec:
            flat[f"PIO_STORAGE_REPOSITORIES_{up}_SOURCE"] = str(spec["source"]).upper()
    for src, spec in (storage.get("sources") or {}).items():
        up = src.upper()
        for pk, pv in spec.items():
            flat[f"PIO_STORAGE_SOURCES_{up}_{pk.upper()}"] = str(pv)
    return flat


def load_config(
    env: Optional[Mapping[str, str]] = None,
    config_file: Optional[os.PathLike] = None,
) -> PioConfig:
    """Resolve config with precedence env > TOML file > defaults."""
    env = dict(env if env is not None else os.environ)
    home = pio_home(env)
    merged = _defaults(home)
    toml_path = Path(config_file) if config_file else None
    if toml_path is None:
        cand = env.get("PIO_CONFIG_FILE")
        if cand:
            toml_path = Path(cand)
        elif (home / "pio-env.toml").exists():
            toml_path = home / "pio-env.toml"
    if toml_path is not None and toml_path.exists():
        merged.update(_load_toml(toml_path))
    merged.update({k: v for k, v in env.items() if k.startswith("PIO_")})

    sources: Dict[str, StorageSourceConfig] = {}
    prefix = "PIO_STORAGE_SOURCES_"
    names = set()
    for key in merged:
        if key.startswith(prefix) and key.endswith("_TYPE"):
            names.add(key[len(prefix):-len("_TYPE")])
    for name in names:
        props = {}
        p = f"{prefix}{name}_"
        for key, val in merged.items():
            if key.startswith(p) and key != f"{p}TYPE":
                props[key[len(p):]] = val
        sources[name] = StorageSourceConfig(
            name=name, type=merged[f"{p}TYPE"], properties=props
        )

    repositories: Dict[str, RepositoryConfig] = {}
    for repo in _REPOSITORIES:
        nk = f"PIO_STORAGE_REPOSITORIES_{repo}_NAME"
        sk = f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE"
        repositories[repo] = RepositoryConfig(
            repo=repo, namespace=merged[nk], source=merged[sk].upper()
        )

    extra = {
        k: v
        for k, v in merged.items()
        if k.startswith("PIO_") and not k.startswith(("PIO_STORAGE_REPOSITORIES_", "PIO_STORAGE_SOURCES_"))
    }
    return PioConfig(home=home, sources=sources, repositories=repositories, extra=extra)
