"""Python binding for the C++ continuous-batching serving frontend.

Architecture (native/serving_frontend.cc): C++ owns sockets, HTTP parsing,
and request batching; Python registers ONE callback that receives a whole
batch and answers it through an engine's serving pipeline — typically via
the engine's vectorized ``batch_predict`` so the XLA program runs once per
batch instead of once per request (SURVEY.md §7 "serving latency").
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
import logging
import threading
from typing import Any, Callable, List, Optional

from predictionio_tpu.native.build import load_library

logger = logging.getLogger(__name__)

__all__ = ["NativeFrontend"]

_BATCH_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int)


class NativeFrontend:
    """Wraps pio_frontend_* for a batch-handler function.

    ``handler(batch: List[dict]) -> List[Any]`` maps parsed query JSONs to
    JSON-able results, one per input (exceptions → per-item 500s).
    """

    def __init__(self, handler: Callable[[List[Any]], List[Any]],
                 host: str = "0.0.0.0", port: int = 8000,
                 max_batch: int = 64, max_wait_us: int = 2000,
                 n_batchers: int = 4):
        lib = load_library("serving_frontend")
        if lib is None:
            raise RuntimeError("native frontend unavailable (g++ build failed)")
        lib.pio_frontend_start.restype = ctypes.c_int
        lib.pio_frontend_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, _BATCH_CB]
        lib.pio_batch_request.restype = ctypes.c_char_p
        lib.pio_batch_request.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
        lib.pio_batch_respond.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int]
        self._lib = lib
        self._handler = handler
        self._host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.max_batch = max_batch
        self.max_wait_us = max_wait_us
        # Batches in flight at once: each batcher thread independently
        # forms a batch and drives the callback, so parse / predict /
        # response writes overlap across batches.
        self.n_batchers = n_batchers
        # Keep a reference — ctypes callbacks are GC'd otherwise.
        self._cb = _BATCH_CB(self._on_batch)

    # -- callback from the C++ batcher thread ------------------------------

    def _on_batch(self, batch_handle, n: int) -> None:
        try:
            datas: List[bytes] = []
            for i in range(n):
                ln = ctypes.c_int(0)
                datas.append(self._lib.pio_batch_request(
                    batch_handle, i, ctypes.byref(ln)) or b"null")
            raw: List[Optional[dict]] = []
            try:
                # One C-level parse for the whole batch instead of n
                # json.loads calls under the GIL.
                raw = json.loads(b"[" + b",".join(datas) + b"]")
            except json.JSONDecodeError:
                raw = []
            if len(raw) != n:
                # Parse failed — or a crafted body like '1,2' smuggled
                # EXTRA array elements through the join, which would
                # misalign every response in the batch.
                raw = []
                for data in datas:  # isolate the malformed item(s)
                    try:
                        raw.append(json.loads(data))
                    except json.JSONDecodeError:
                        raw.append(None)
            # Malformed JSON answered inline; valid ones go to the handler.
            valid_idx = [i for i, r in enumerate(raw) if r is not None]
            results: List[Any] = [None] * n
            if valid_idx:
                try:
                    outs = self._handler([raw[i] for i in valid_idx])
                    for i, out in zip(valid_idx, outs):
                        results[i] = (200, out)
                except Exception:
                    logger.exception("batch handler failed")
                    for i in valid_idx:
                        results[i] = (500, {"message": "Internal server error."})
            for i in range(n):
                if raw[i] is None:
                    results[i] = (400, {"message": "Invalid JSON."})
            for i, (status, payload) in enumerate(results):
                body = json.dumps(payload).encode()
                self._lib.pio_batch_respond(batch_handle, i, body, len(body),
                                            status)
        except Exception:
            logger.exception("native frontend callback error")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        port = self._lib.pio_frontend_start(
            self._host.encode(), self._requested_port, self.max_batch,
            self.max_wait_us, self.n_batchers, self._cb)
        if port < 0:
            raise RuntimeError(f"pio_frontend_start failed ({port})")
        self.port = port
        logger.info("Native serving frontend on %s:%d (max_batch=%d)",
                    self._host, port, self.max_batch)
        return port

    def stop(self) -> None:
        self._lib.pio_frontend_stop()
