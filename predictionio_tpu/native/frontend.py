"""Python binding for the C++ continuous-batching serving frontend.

Architecture (native/serving_frontend.cc): C++ owns sockets, HTTP parsing,
and request batching; Python registers ONE callback that receives a whole
batch and answers it through an engine's serving pipeline — typically via
the engine's vectorized ``batch_predict`` so the XLA program runs once per
batch instead of once per request (SURVEY.md §7 "serving latency").
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
import logging
import threading
import time
from typing import Any, Callable, List, Optional

from predictionio_tpu.native.build import load_library

logger = logging.getLogger(__name__)

__all__ = ["NativeFrontend"]

_BATCH_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int)


class NativeFrontend:
    """Wraps pio_frontend_* for a batch-handler function.

    ``handler(batch: List[dict]) -> List[Any]`` maps parsed query JSONs
    (POST /queries.json) to JSON-able results, one per input (exceptions
    → per-item 500s).  ``fallback(method, path_with_query, body) ->
    (status, payload)`` answers every OTHER route the C++ layer forwards
    (event ingest, webhooks, reload, …); without one those routes 404.
    Same-route fallback items within a batch are handed to
    ``fallback_batch(method, path, bodies) -> [(status, payload), ...]``
    when provided — the event server uses this for group-committed
    ingest.
    """

    def __init__(self, handler: Optional[Callable[[List[Any]], List[Any]]],
                 host: str = "0.0.0.0", port: int = 8000,
                 max_batch: int = 64, max_wait_us: int = 2000,
                 n_batchers: int = 4,
                 fallback: Optional[Callable[[str, str, bytes],
                                             Any]] = None,
                 fallback_batch: Optional[Callable[[str, str, List[bytes]],
                                                   List[Any]]] = None,
                 plugin_hook: Optional[Callable[[str, int, float],
                                                str]] = None):
        lib = load_library("serving_frontend")
        if lib is None:
            raise RuntimeError("native frontend unavailable (g++ build failed)")
        lib.pio_frontend_start.restype = ctypes.c_int
        lib.pio_frontend_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, _BATCH_CB]
        lib.pio_batch_request.restype = ctypes.c_char_p
        lib.pio_batch_request.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
        lib.pio_batch_route.restype = ctypes.c_char_p
        lib.pio_batch_route.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.pio_batch_respond.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_char_p]
        lib.pio_batch_respond_ex.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                             ctypes.c_char_p, ctypes.c_int,
                                             ctypes.c_int, ctypes.c_char_p,
                                             ctypes.c_char_p]
        self._lib = lib
        self._handler = handler
        self._fallback = fallback
        self._fallback_batch = fallback_batch
        # Server plugin seam: ``plugin_hook(route, status, ms) -> str``
        # returns CRLF-joined header lines to inject into the response
        # (PluginManager.header_block); responses then go through
        # pio_batch_respond_ex so the C++ writer emits them.
        self._plugin_hook = plugin_hook
        self._host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.max_batch = max_batch
        self.max_wait_us = max_wait_us
        # Batches in flight at once: each batcher thread independently
        # forms a batch and drives the callback, so parse / predict /
        # response writes overlap across batches.
        self.n_batchers = n_batchers
        # Keep a reference — ctypes callbacks are GC'd otherwise.
        self._cb = _BATCH_CB(self._on_batch)

    # -- callback from the C++ batcher thread ------------------------------

    def _on_batch(self, batch_handle, n: int) -> None:
        t0 = time.perf_counter()
        try:
            datas: List[bytes] = []
            routes: List[str] = []
            for i in range(n):
                ln = ctypes.c_int(0)
                datas.append(self._lib.pio_batch_request(
                    batch_handle, i, ctypes.byref(ln)) or b"")
                routes.append((self._lib.pio_batch_route(
                    batch_handle, i, ctypes.byref(ln)) or b"").decode(
                        "utf-8", "replace"))

            # Split query-path items (POST only, like the python server)
            # from everything else the C++ layer forwarded (event ingest,
            # webhooks, reload, ...).  With no query handler
            # (event-server mode) EVERY item is fallback.
            fb_idx = [i for i, r in enumerate(routes)
                      if self._handler is None
                      or not r.startswith("POST ")
                      or r.split(" ", 1)[-1].split("?", 1)[0]
                      != "/queries.json"]
            if fb_idx:
                self._dispatch_mixed(batch_handle, n, datas, routes,
                                     set(fb_idx), t0)
                return
            self._answer_queries(batch_handle, range(n), datas, t0)
        except Exception:
            logger.exception("native frontend callback error")

    def _dispatch_mixed(self, batch_handle, n, datas, routes, fb_set, t0):
        results: List[Any] = [None] * n
        # Consecutive same-route fallback runs batch together (the event
        # server group-commits a run of POST /events.json singles).
        i = 0
        while i < n:
            if i not in fb_set:
                i += 1
                continue
            j = i
            while j < n and j in fb_set and routes[j] == routes[i]:
                j += 1
            method, _, path = routes[i].partition(" ")
            group = list(range(i, j))
            try:
                if self._fallback_batch is not None:
                    outs = self._fallback_batch(method, path,
                                                [datas[g] for g in group])
                elif self._fallback is not None:
                    outs = [self._fallback(method, path, datas[g])
                            for g in group]
                else:
                    outs = [(404, {"message": "Not Found"})] * len(group)
                # Every item MUST get a response: an unanswered Pending
                # blocks its C++ worker forever (and stop() then deadlocks
                # joining it), so a miscounting handler fails safe here.
                if len(outs) != len(group) or any(
                        not isinstance(o, tuple) or len(o) not in (2, 3)
                        for o in outs):
                    raise ValueError(
                        f"fallback returned {len(outs)} results for "
                        f"{len(group)} requests")
            except Exception:
                logger.exception("fallback handler failed")
                outs = [(500, {"message": "Internal server error."})] \
                    * len(group)
            for g, out in zip(group, outs):
                results[g] = out
            i = j
        for i, res in enumerate(results):
            if res is None:
                continue
            self._respond(batch_handle, i, res, routes[i], t0)
        q_idx = [i for i in range(n) if i not in fb_set]
        if q_idx:
            self._answer_queries(batch_handle, q_idx,
                                 [datas[i] for i in q_idx], t0)

    def _respond(self, batch_handle, i, res, route: str, t0: float) -> None:
        """Encode + answer one Pending, injecting plugin headers when the
        server's plugin hook returns any (pio_batch_respond_ex)."""
        status, body, ctype = self._encode(res)
        if self._plugin_hook is not None:
            try:
                # "METHOD /path" only — the query string may carry an
                # accessKey and the python transport doesn't pass it either
                extra = self._plugin_hook(
                    route.split("?", 1)[0], status,
                    (time.perf_counter() - t0) * 1e3)
            except Exception:
                logger.exception("plugin hook failed")
                extra = ""
            if extra:
                self._lib.pio_batch_respond_ex(
                    batch_handle, i, body, len(body), status, ctype,
                    extra.encode())
                return
        self._lib.pio_batch_respond(batch_handle, i, body, len(body),
                                    status, ctype)

    def _answer_queries(self, batch_handle, idxs, datas,
                        t0: Optional[float] = None) -> None:
        if t0 is None:
            t0 = time.perf_counter()
        idxs = list(idxs)
        try:
            raw: List[Optional[dict]] = []
            try:
                # One C-level parse for the whole batch instead of n
                # json.loads calls under the GIL.  Empty bodies become
                # `null` placeholders (-> per-item 400 below).
                raw = json.loads(b"[" + b",".join(d if d else b"null"
                                                 for d in datas) + b"]")
            except json.JSONDecodeError:
                raw = []
            if len(raw) != len(idxs):
                # Parse failed — or a crafted body like '1,2' smuggled
                # EXTRA array elements through the join, which would
                # misalign every response in the batch.
                raw = []
                for data in datas:  # isolate the malformed item(s)
                    try:
                        raw.append(json.loads(data))
                    except json.JSONDecodeError:
                        raw.append(None)
            # Malformed JSON answered inline; valid ones go to the handler.
            valid = [k for k, r in enumerate(raw) if r is not None]
            results: List[Any] = [None] * len(idxs)
            if valid:
                try:
                    outs = self._handler([raw[k] for k in valid])
                    # Miscounting handlers fail safe (same invariant as
                    # the fallback path: every Pending MUST be answered
                    # or its C++ worker blocks forever).
                    if len(outs) != len(valid):
                        raise ValueError(
                            f"handler returned {len(outs)} results for "
                            f"{len(valid)} queries")
                    for k, out in zip(valid, outs):
                        results[k] = (200, out)
                except Exception:
                    logger.exception("batch handler failed")
                    for k in valid:
                        results[k] = (500, {"message": "Internal server error."})
            for k in range(len(idxs)):
                if raw[k] is None:
                    results[k] = (400, {"message": "Invalid JSON."})
            for k, res in enumerate(results):
                self._respond(batch_handle, idxs[k], res,
                              "POST /queries.json", t0)
        except Exception:
            logger.exception("native frontend callback error")

    @staticmethod
    def _encode(res) -> "tuple[int, bytes, bytes]":
        """(status, payload[, content_type]) → (status, body, ctype).

        A non-JSON-able payload must not abort the response loop (every
        unanswered Pending hangs its C++ worker), so it degrades to a
        per-item 500.  A handler that needs a specific content type on
        the wire (the /metrics Prometheus exposition) returns a 3-tuple;
        bare string payloads default to plain UTF-8 text.
        """
        if len(res) == 3:
            status, payload, ctype = res
            try:
                body = (payload.encode() if isinstance(payload, str)
                        else payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode())
                if isinstance(ctype, str):
                    ctype = ctype.encode()
                return status, body, ctype
            except (TypeError, ValueError, AttributeError):
                logger.exception("non-serializable 3-tuple response")
                return (500, b'{"message": "Internal server error."}',
                        b"application/json; charset=UTF-8")
        status, payload = res
        if isinstance(payload, str):
            return status, payload.encode(), b"text/plain; charset=utf-8"
        try:
            return (status, json.dumps(payload).encode(),
                    b"application/json; charset=UTF-8")
        except (TypeError, ValueError):
            logger.exception("non-serializable response payload")
            return (500, b'{"message": "Internal server error."}',
                    b"application/json; charset=UTF-8")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        # Event-server mode (no query handler): / and /metrics forward to
        # Python too, so the event server's own status page and ingest
        # metrics stay reachable behind the native layer.
        forward_all = 1 if self._handler is None else 0
        port = self._lib.pio_frontend_start(
            self._host.encode(), self._requested_port, self.max_batch,
            self.max_wait_us, self.n_batchers, forward_all, self._cb)
        if port < 0:
            raise RuntimeError(f"pio_frontend_start failed ({port})")
        self.port = port
        logger.info("Native serving frontend on %s:%d (max_batch=%d)",
                    self._host, port, self.max_batch)
        return port

    def stop(self) -> None:
        self._lib.pio_frontend_stop()
