"""Native (C++) runtime components and their ctypes bindings.

Reference mandate (SURVEY.md §2.3): the serving frontend and the event-log
feeder are native, not Python stand-ins.  Sources live in ``native/`` at
the repo root; :func:`build.load_library` compiles them on first use with
g++ (no pybind11 in the image — plain ``extern "C"`` + ctypes) and caches
the .so next to the sources.
"""

from predictionio_tpu.native.build import load_library, native_available

__all__ = ["load_library", "native_available"]
