"""Python binding for the C++ event-log feeder (native/feeder.cc).

Write path: :func:`write_cache` converts indexed COO interactions (the
output of a template DataSource) into the mmap-able PIOF1 columnar cache
(version 3: any number of categorical u32 id columns — real CTR shapes —
plus optional extra f32 feature columns, e.g. DLRM dense features; v1/v2
files remain readable).
Read path: :class:`EventFeeder` iterates shuffled batches assembled by the
native library — numpy buffers are passed straight into C (no copies on
the C side; the arrays handed back are the reusable buffers).
"""

from __future__ import annotations

import ctypes
import struct
import time
from pathlib import Path
from typing import Iterator, Optional, Tuple

import numpy as np

from predictionio_tpu.native.build import load_library
from predictionio_tpu.obs import get_registry, publish_event

__all__ = ["write_cache", "EventFeeder"]

_MAGIC = b"PIOF1"


def write_cache(path, user_ids=None, item_ids=None, values=None, times=None,
                extras=None, cats=None) -> Path:
    """Write the PIOF1 v3 binary columnar event cache.

    Either ``user_ids`` + ``item_ids`` (the classic 2-column case) or
    ``cats`` — an ``[n, F]`` uint32 matrix of F categorical id columns
    (e.g. a real CTR shape with tens of fields) — must be given.
    ``extras``: optional ``[n, n_extra]`` float32 feature matrix, stored
    column-major per the format (native/feeder.cc header comment).
    """
    path = Path(path)
    if cats is None:
        if user_ids is None or item_ids is None:
            raise ValueError("write_cache needs user_ids+item_ids or cats")
        cats = np.stack([np.asarray(user_ids), np.asarray(item_ids)], axis=1)
    cats = np.ascontiguousarray(cats, dtype=np.uint32)
    if cats.ndim == 1:
        cats = cats[:, None]
    n, n_cat = cats.shape
    if not 1 <= n_cat <= 1024:
        # Mirror the reader's bound — fail at the writer, loudly.
        raise ValueError(f"n_cat must be in [1, 1024], got {n_cat}")
    if values is None:
        values = np.ones(n, dtype=np.float32)
    if times is None:
        times = np.zeros(n, dtype=np.int64)
    values = np.ascontiguousarray(values, dtype=np.float32)
    times = np.ascontiguousarray(times, dtype=np.int64)
    if extras is not None:
        extras = np.ascontiguousarray(extras, dtype=np.float32)
        if extras.ndim == 1:
            extras = extras[:, None]
        assert extras.shape[0] == n, "extras rows must match event count"
    n_extra = 0 if extras is None else extras.shape[1]
    if n_extra > 65536:
        # Mirror the reader's bound — fail at the writer, loudly.
        raise ValueError(f"n_extra must be <= 65536, got {n_extra}")
    with open(path, "wb") as f:
        f.write(_MAGIC + b"\x00" + struct.pack("<H", 3))
        f.write(struct.pack("<Q", n))
        f.write(struct.pack("<II", n_extra, n_cat))
        for c in range(n_cat):
            f.write(np.ascontiguousarray(cats[:, c]).tobytes())
        f.write(values.tobytes())
        pos = 24 + n * (4 * n_cat + 4)
        f.write(b"\x00" * (-pos % 8))  # times are 8-byte aligned in v2+
        f.write(times.tobytes())
        for c in range(n_extra):
            f.write(np.ascontiguousarray(extras[:, c]).tobytes())
    return path


class EventFeeder:
    """Shuffled minibatch iterator over a PIOF1 cache, assembly in C++."""

    def __init__(self, path, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True):
        lib = load_library("feeder")
        if lib is None:
            raise RuntimeError("native feeder unavailable (g++ build failed)")
        lib.pio_feeder_open.restype = ctypes.c_void_p
        lib.pio_feeder_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_int]
        lib.pio_feeder_num_rows.restype = ctypes.c_int64
        lib.pio_feeder_num_rows.argtypes = [ctypes.c_void_p]
        lib.pio_feeder_n_extra.restype = ctypes.c_int32
        lib.pio_feeder_n_extra.argtypes = [ctypes.c_void_p]
        lib.pio_feeder_n_cat.restype = ctypes.c_int32
        lib.pio_feeder_n_cat.argtypes = [ctypes.c_void_p]
        lib.pio_feeder_next_batch.restype = ctypes.c_int64
        lib.pio_feeder_next_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float)]  # extras [batch, n_extra]
        lib.pio_feeder_next_batch_cats.restype = ctypes.c_int64
        lib.pio_feeder_next_batch_cats.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),  # cats [batch, n_cat]
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float)]
        lib.pio_feeder_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.pio_feeder_open(str(path).encode(), seed, int(shuffle))
        if not self._h:
            raise RuntimeError(f"cannot open event cache {path!r}")
        self.batch_size = batch_size
        self.n_extra = int(lib.pio_feeder_n_extra(self._h))
        self.n_cat = int(lib.pio_feeder_n_cat(self._h))
        # Pipeline observability (ISSUE: decompose the feeder→device gap):
        # wait time per native assembly call + how much of the epoch is
        # still queued behind the training loop.
        reg = get_registry()
        self._m_wait = reg.histogram(
            "pio_feeder_wait_ms",
            "Host wait per native batch-assembly call.")
        self._m_batches = reg.counter(
            "pio_feeder_batches_total", "Batches served by the feeder.")
        self._m_rows = reg.counter(
            "pio_feeder_rows_total", "Rows served by the feeder.")
        self._m_depth = reg.gauge(
            "pio_feeder_queue_depth",
            "Rows remaining in the feeder's current epoch.")
        self._epoch_served = 0
        self._m_depth.set(int(lib.pio_feeder_num_rows(self._h)))
        self._users = np.empty(batch_size, np.uint32)
        self._items = np.empty(batch_size, np.uint32)
        self._cats = np.empty((batch_size, self.n_cat), np.uint32)
        self._vals = np.empty(batch_size, np.float32)
        self._times = np.empty(batch_size, np.int64)
        self._extras = (np.empty((batch_size, self.n_extra), np.float32)
                        if self.n_extra else None)

    def __len__(self) -> int:
        return int(self._lib.pio_feeder_num_rows(self._h))

    def _extras_ptr(self):
        return (self._extras.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                if self._extras is not None
                else ctypes.cast(None, ctypes.POINTER(ctypes.c_float)))

    def _finish_batch(self, n, lead, wait_ms: float):
        """Shared batch tail: error/epoch-boundary handling, metrics,
        copies."""
        if n < 0:
            raise RuntimeError("feeder error")
        self._m_wait.observe(wait_ms)
        if n == 0:
            # Epoch boundary: the whole dataset is queued again.  The
            # trace-ring event correlates feeder epoch turnover with
            # whatever request/run is being explained.
            publish_event("feeder.epoch", rows=self._epoch_served,
                          batchSize=self.batch_size)
            self._epoch_served = 0
            self._m_depth.set(len(self))
            return None
        n = int(n)
        self._m_batches.inc()
        self._m_rows.inc(n)
        self._epoch_served += n
        self._m_depth.set(max(len(self) - self._epoch_served, 0))
        out = tuple(a[:n].copy() for a in lead) + (self._vals[:n].copy(),)
        if self._extras is not None:
            out = out + (self._extras[:n].copy(),)
        return out

    def next_batch(self) -> Optional[Tuple[np.ndarray, ...]]:
        """One batch of (users, items, values[, extras]); None at an epoch
        boundary."""
        if self.n_cat < 2:
            raise RuntimeError(
                f"cache has {self.n_cat} categorical column(s); the legacy "
                "(users, items) batch API needs >= 2 — use "
                "next_batch_cats()/epoch_cats()")
        t0 = time.perf_counter()
        n = self._lib.pio_feeder_next_batch(
            self._h, self.batch_size,
            self._users.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self._items.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self._vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._extras_ptr())
        return self._finish_batch(n, (self._users, self._items),
                                  (time.perf_counter() - t0) * 1e3)

    def next_batch_cats(self) -> Optional[Tuple[np.ndarray, ...]]:
        """One batch of (cats [n, n_cat], values[, extras]); None at an
        epoch boundary.  Works for ANY column count (v3 caches)."""
        t0 = time.perf_counter()
        n = self._lib.pio_feeder_next_batch_cats(
            self._h, self.batch_size,
            self._cats.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            self._vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            self._extras_ptr())
        return self._finish_batch(n, (self._cats,),
                                  (time.perf_counter() - t0) * 1e3)

    def epoch(self) -> Iterator[Tuple[np.ndarray, ...]]:
        while True:
            b = self.next_batch()
            if b is None:
                return
            yield b

    def epoch_cats(self) -> Iterator[Tuple[np.ndarray, ...]]:
        while True:
            b = self.next_batch_cats()
            if b is None:
                return
            yield b

    def close(self) -> None:
        if self._h:
            self._lib.pio_feeder_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
