"""Compile-on-first-use loader for the C++ components.

Keeps the build chain dependency-free: one ``g++ -O2 -shared`` invocation
per translation unit, cached by source mtime.  (The reference's equivalent
is sbt/assembly — SURVEY.md §2.1 build glue.)
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Dict, Optional

logger = logging.getLogger(__name__)

__all__ = ["load_library", "native_available", "NATIVE_DIR"]

NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_cache: Dict[str, ctypes.CDLL] = {}
_lock = threading.Lock()


def _build(src: Path, out: Path) -> bool:
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
           str(src), "-o", str(out)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        err = getattr(e, "stderr", b"") or b""
        logger.warning("native build failed for %s: %s", src.name,
                       err.decode(errors="replace")[:2000])
        return False


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Load ``native/<name>.cc`` as a shared library (build if stale)."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = NATIVE_DIR / f"{name}.cc"
        if not src.exists():
            logger.warning("native source %s missing", src)
            return None
        out = NATIVE_DIR / f"lib{name}.so"
        if not out.exists() or out.stat().st_mtime < src.stat().st_mtime:
            if not _build(src, out):
                return None
        try:
            lib = ctypes.CDLL(str(out))
        except OSError as e:
            logger.warning("cannot dlopen %s: %s", out, e)
            return None
        _cache[name] = lib
        return lib


def native_available(name: str) -> bool:
    return load_library(name) is not None
