"""Online learning: the event→model refresh loop (ISSUE 10).

The DASE architecture ingests behavioral events continuously, but until
this subsystem models only changed on a manual ``pio train``.  This
package closes the loop:

- **Delta warm-start** — ``run_train(warm_from=...)`` restores the last
  COMPLETED generation's carried state and continues training on only
  the delta window of events.  The window is anchored by the **data
  watermark** every train run records on its EngineInstance
  (``workflow.core_workflow.data_watermark``): the next refresh reads
  ``[previous watermark, new watermark)``, so windows never gap or
  overlap.  Algorithms that cannot continue (ALS) raise
  :class:`~predictionio_tpu.controller.WarmStartFallback` and the run
  retrains fully — a cycle always lands a generation.
- **Serve-time fold-in** — ALS answers UNSEEN users by solving one
  ridge system against the frozen item factors from the user's recent
  events (``models.als.fold_in``), cached per generation.  Per-process
  and ephemeral: the next refresh trains the user in.
- **Refresh daemon** (:mod:`predictionio_tpu.refresh.daemon`) —
  ``pio train --follow`` retrains on a cadence, each run supervised by
  the PR-4 machinery (watchdog / divergence rollback / preemption,
  which live inside the train loops), promoted ONLY through the engine
  server's staged-reload canary gate (``POST /reload`` — never a direct
  model write; ``tools/lint_refresh.py`` pins this), and auto-rolled
  back if the PR-9 SLO burn trips within the canary window.

Freshness is first-class observability:

====================================  ==================================
``pio_refresh_runs_total{result}``    refresh cycles by outcome
                                      (warm / full / full_fallback /
                                      failed)
``pio_refresh_promotions_total        staged-reload promotions by
{result}``                            outcome (promoted / rolled_back /
                                      rejected / error / skipped)
``pio_refresh_staleness_s``           event→servable staleness: ingest
                                      high-watermark minus the promoted
                                      generation's data watermark
``pio_refresh_train_s{mode}``         wall seconds of the last refresh
                                      train by mode
``pio_events_latest_ts{app}``         (event server) ingest
                                      high-watermark, epoch seconds
====================================  ==================================

Env knobs (all read by :meth:`RefreshConfig.from_env`):

====================================  ==================================
``PIO_REFRESH_INTERVAL_S``            follow-mode cadence (default 300)
``PIO_REFRESH_MAX_DELTA_FRACTION``    delta/corpus ratio above which a
                                      warm start falls back to a full
                                      retrain (default 0.5)
``PIO_REFRESH_EVAL_TOLERANCE``        allowed relative regression of the
                                      warm-started model on the delta
                                      sample before falling back (0.1)
``PIO_REFRESH_PROMOTE_URL``           engine-server base URL promotions
                                      go through (unset = train only,
                                      no promotion)
``PIO_REFRESH_CANARY_WINDOW_S``       post-promotion SLO watch window
                                      (default 60; 0 = no watch)
``PIO_REFRESH_CANARY_POLL_S``         SLO poll cadence in the window (2)
``PIO_FOLD_IN``                       serve-time ALS fold-in on/off (on)
``PIO_FOLD_IN_EVENTS``                events per fold-in solve (50)
``PIO_FOLD_IN_CACHE``                 folded users kept per generation
                                      (10000)
====================================  ==================================
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import os
from typing import Any, List, Optional

from predictionio_tpu.controller import WarmStartFallback
from predictionio_tpu.data.storage.base import EngineInstance, epoch_us
from predictionio_tpu.obs import get_registry
from predictionio_tpu.workflow.core_workflow import (
    DATA_WATERMARK_KEY,
    data_watermark,
)

__all__ = [
    "RefreshConfig",
    "WarmStartContext",
    "WarmStartFallback",
    "RefreshMetrics",
    "staleness_s",
    "data_watermark",
    "DATA_WATERMARK_KEY",
]


def _env_f(key: str, default: float) -> float:
    raw = os.environ.get(key)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


def _env_opt_f(key: str) -> Optional[float]:
    raw = os.environ.get(key)
    if raw is None or str(raw).strip() == "":
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


@dataclasses.dataclass
class RefreshConfig:
    """Refresh-loop knobs; :meth:`from_env` is the production
    constructor (CLI flags override, same pattern as SchedulerConfig).

    Trigger mode (ISSUE 11 satellite, carried since PR 10): with either
    ``trigger_staleness_s`` or ``trigger_delta_count`` set, the daemon
    fires a cycle when the event→servable staleness or the count of
    events ingested past the served watermark crosses its threshold —
    the freshness gauges become actuators — polling every
    ``trigger_poll_s``, with the fixed ``interval_s`` cadence kept as a
    backstop ceiling between cycles."""

    interval_s: float = 300.0
    max_delta_fraction: float = 0.5
    eval_tolerance: float = 0.1
    promote_url: Optional[str] = None
    canary_window_s: float = 60.0
    canary_poll_s: float = 2.0
    trigger_staleness_s: Optional[float] = None
    trigger_delta_count: Optional[int] = None
    trigger_poll_s: float = 5.0

    @classmethod
    def from_env(cls, **overrides) -> "RefreshConfig":
        delta_n = _env_opt_f("PIO_REFRESH_TRIGGER_DELTA_COUNT")
        cfg = cls(
            interval_s=_env_f("PIO_REFRESH_INTERVAL_S", 300.0),
            max_delta_fraction=_env_f("PIO_REFRESH_MAX_DELTA_FRACTION", 0.5),
            eval_tolerance=_env_f("PIO_REFRESH_EVAL_TOLERANCE", 0.1),
            promote_url=(os.environ.get("PIO_REFRESH_PROMOTE_URL") or None),
            canary_window_s=_env_f("PIO_REFRESH_CANARY_WINDOW_S", 60.0),
            canary_poll_s=_env_f("PIO_REFRESH_CANARY_POLL_S", 2.0),
            trigger_staleness_s=_env_opt_f(
                "PIO_REFRESH_TRIGGER_STALENESS_S"),
            trigger_delta_count=(int(delta_n) if delta_n is not None
                                 else None),
            trigger_poll_s=_env_f("PIO_REFRESH_TRIGGER_POLL_S", 5.0),
        )
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg


@dataclasses.dataclass
class WarmStartContext:
    """Everything a warm (delta) train run needs about its parent
    generation.  ``models`` aligns with the engine's algorithm list —
    ``Algorithm.warm_start`` receives its own previous model."""

    instance: EngineInstance
    models: List[Any]
    start_time: _dt.datetime            # parent's data watermark
    max_delta_fraction: float = 0.5
    eval_tolerance: float = 0.1


def staleness_s(latest_event_time: Optional[_dt.datetime],
                serving_watermark: Optional[_dt.datetime]) -> Optional[float]:
    """Event→servable staleness: how far the ingest high-watermark runs
    ahead of the serving generation's data watermark.  None when either
    side is unknown (no events yet / pre-ISSUE-10 instance); floored at
    0 — a watermark past the newest event means everything ingested is
    already servable."""
    if latest_event_time is None or serving_watermark is None:
        return None
    return max(
        0.0,
        (epoch_us(latest_event_time) - epoch_us(serving_watermark)) / 1e6)


class RefreshMetrics:
    """The refresh loop's instruments over the shared registry."""

    def __init__(self, registry=None):
        reg = registry or get_registry()
        self.runs = reg.counter(
            "pio_refresh_runs_total",
            "Refresh train cycles by outcome (warm/full/full_fallback/"
            "failed).", ("result",))
        self.promotions = reg.counter(
            "pio_refresh_promotions_total",
            "Refresh promotions through the staged-reload gate by outcome.",
            ("result",))
        self.staleness = reg.gauge(
            "pio_refresh_staleness_s",
            "Event→servable staleness: ingest high-watermark minus the "
            "promoted generation's data watermark, seconds.")
        self.train_s = reg.gauge(
            "pio_refresh_train_s",
            "Wall seconds of the last refresh train run by mode.",
            ("mode",))
        self.triggers = reg.counter(
            "pio_refresh_triggers_total",
            "Trigger-mode refresh firings by reason (staleness / "
            "delta_count / interval).", ("reason",))
