"""The refresh daemon: ``pio train --follow`` (ISSUE 10).

One :meth:`RefreshDaemon.run_once` is one closed loop iteration:

1. resolve the last COMPLETED generation and its data watermark,
2. load its models and build a :class:`~predictionio_tpu.refresh.
   WarmStartContext` (no watermark / no models → full retrain),
3. ``run_train(warm_from=...)`` — the delta read, the warm-vs-full
   fallback, and ALL of the PR-4 supervision (watchdog, divergence
   rollback, preemption) happen inside the workflow/train loops,
4. promote the new instance through the serving server's STAGED-RELOAD
   canary gate (``POST /reload``) — never a direct model write; a
   validation-rejected candidate (409) leaves the old generation
   serving,
5. watch the PR-9 SLO burn for the canary window and ``POST
   /admin/rollback`` if it trips,
6. publish freshness: ``pio_refresh_staleness_s`` from the ingest
   high-watermark vs the served generation's data watermark.

A failed cycle (diverged train, unreachable server) records its outcome
and the daemon keeps following — the previous generation keeps serving
throughout, which is the whole point of promoting through the gate.

Clock / sleep / HTTP are injectable so the test matrix drives canary
windows and follow cadences with zero wall sleeps.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, Optional
from urllib.request import Request, urlopen

from predictionio_tpu.controller import Engine, EngineVariant, RuntimeContext
from predictionio_tpu.obs import publish_event, trace as obs_trace
from predictionio_tpu.resilience.supervision import TrainPreempted
from predictionio_tpu.refresh import (
    RefreshConfig,
    RefreshMetrics,
    WarmStartContext,
    data_watermark,
    staleness_s,
)
from predictionio_tpu.version import __version__
from predictionio_tpu.workflow.core_workflow import (
    REFRESH_MODE_KEY,
    load_models,
    run_train,
)

logger = logging.getLogger(__name__)

__all__ = ["RefreshDaemon", "HttpPromoter", "PromotionRejected"]


class PromotionRejected(RuntimeError):
    """The staged-reload gate refused the candidate (validation/canary
    failure → HTTP 409).  The previous generation keeps serving."""


def _http_json(url: str, method: str = "GET", timeout: float = 30.0,
               opener: Callable = urlopen) -> tuple:
    req = Request(url, method=method)
    with opener(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


class HttpPromoter:
    """Promotes a freshly trained instance through a live engine
    server's staged-reload gate, then watches the SLO burn for the
    canary window.

    The ONLY writes this class performs are ``POST /reload`` and
    ``POST /admin/rollback`` — the refresh loop never touches the model
    store or the server's generation state directly
    (``tools/lint_refresh.py`` makes that structural).
    """

    def __init__(self, base_url: str, *,
                 canary_window_s: float = 60.0,
                 canary_poll_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 opener: Callable = urlopen):
        self.base_url = base_url.rstrip("/")
        self.canary_window_s = float(canary_window_s)
        self.canary_poll_s = max(float(canary_poll_s), 0.05)
        self._clock = clock
        self._sleep = sleep
        self._opener = opener

    def promote(self, instance_id: str) -> Dict[str, Any]:
        """``POST /reload``: read → build → validate → canary → swap on
        the server.  Raises :class:`PromotionRejected` on 409 (candidate
        failed validation; last-good keeps serving)."""
        from urllib.error import HTTPError

        try:
            status, body = _http_json(self.base_url + "/reload", "POST",
                                      opener=self._opener)
        except HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload).get("message", "")
            except Exception:
                msg = payload.decode(errors="replace")[:200]
            if e.code == 409:
                raise PromotionRejected(
                    f"staged reload rejected the candidate: {msg}") from e
            raise
        loaded = body.get("engineInstanceId")
        if loaded != instance_id:
            # Another train raced us to COMPLETED; the server loaded the
            # newest one — louder than silent, but not an error: the
            # serving model is still fresher than before.
            logger.warning("promotion loaded instance %s, not the refresh's "
                           "%s (a newer COMPLETED run won the race)",
                           loaded, instance_id)
        return body

    def slo_state(self) -> Dict[str, Any]:
        _, body = _http_json(self.base_url + "/stats.json",
                             opener=self._opener)
        return body.get("slo") or {}

    def quality_state(self) -> Dict[str, Any]:
        """The server's ``/quality.json`` gate document (ISSUE 11).
        Empty on any failure — an old server without the endpoint, or a
        poll blip, must never trip a rollback."""
        try:
            _, body = _http_json(self.base_url + "/quality.json",
                                 opener=self._opener)
        except Exception:
            return {}
        return body if isinstance(body, dict) else {}

    def served_watermark(self):
        """The data watermark of the generation the server is ACTUALLY
        serving right now — the authoritative anchor for the staleness
        gauge (a rejected or rolled-back promotion leaves the old
        watermark in place, and the gauge must say so)."""
        import datetime as _dt

        _, body = _http_json(self.base_url + "/", opener=self._opener)
        raw = body.get("dataWatermark")
        return _dt.datetime.fromisoformat(raw) if raw else None

    def _burn_tripped(self, slo: Dict[str, Any]) -> bool:
        if slo.get("degraded"):
            return True
        thr = float(slo.get("threshold") or 14.4)
        fast = slo.get("burn", {}).get("fast", {})
        return max(float(fast.get("availability", 0.0)),
                   float(fast.get("latency", 0.0))) >= thr

    @staticmethod
    def _quality_tripped(quality: Dict[str, Any]) -> bool:
        """The server-side quality gate verdict (ISSUE 11): drift over
        threshold on both windows, shadow-canary divergence, or — since
        ISSUE 16 — sampled retrieval-recall regression vs the
        generation's own baked scorecard (``gate.reasons`` carries
        ``recall_regression``); the cold pass-throughs and the
        ``PIO_QUALITY_GATE`` / ``PIO_RECALL_GATE`` switches are already
        applied by the server, so the daemon reads ONE bit."""
        gate = quality.get("gate") or {}
        return bool(gate.get("rollback"))

    def rollback(self) -> None:
        _http_json(self.base_url + "/admin/rollback", "POST",
                   opener=self._opener)

    def canary_watch(self) -> str:
        """Poll the server's SLO *and* quality state for the canary
        window; roll back when either trips — a promotion that burns
        prediction quality rolls back exactly as one that burns the
        latency SLO.  Returns ``"promoted"`` or ``"rolled_back"``."""
        deadline = self._clock() + self.canary_window_s
        while self._clock() < deadline:
            try:
                slo = self.slo_state()
            except Exception:
                logger.warning("canary SLO poll failed; continuing watch",
                               exc_info=True)
                slo = {}
            if self._burn_tripped(slo):
                logger.warning("SLO burn tripped inside the canary window "
                               "(%s) — rolling the promotion back",
                               slo.get("tripReasons") or "degraded")
                self.rollback()
                return "rolled_back"
            quality = self.quality_state()
            if self._quality_tripped(quality):
                logger.warning(
                    "quality gate tripped inside the canary window (%s) — "
                    "rolling the promotion back",
                    (quality.get("gate") or {}).get("reasons")
                    or "degraded")
                self.rollback()
                return "rolled_back"
            self._sleep(self.canary_poll_s)
        return "promoted"


class RefreshDaemon:
    """Follow-mode retraining on a cadence (``pio train --follow``)."""

    def __init__(self, engine: Engine, variant: EngineVariant,
                 ctx: Optional[RuntimeContext] = None, *,
                 config: Optional[RefreshConfig] = None,
                 promoter: Optional[HttpPromoter] = None,
                 engine_id: Optional[str] = None,
                 engine_version: str = __version__,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.engine = engine
        self.variant = variant
        self.ctx = ctx or RuntimeContext.create()
        self.config = config or RefreshConfig.from_env()
        self.engine_id = engine_id or variant.engine_factory
        self.engine_version = engine_version
        self._clock = clock
        self.metrics = RefreshMetrics(registry)
        self.stop_event = threading.Event()
        if promoter is None and self.config.promote_url:
            urls = [u.strip() for u in self.config.promote_url.split(",")
                    if u.strip()]
            if len(urls) > 1:
                # Fleet mode (ISSUE 15): N instance URLs promote through
                # the wave-based rollout controller — gated waves, fleet
                # SLO/quality gate, whole-fleet rollback — never a bare
                # promote loop (tools/lint_refresh.py rule 4).
                from predictionio_tpu.fleet import FleetPromoter

                promoter = FleetPromoter(urls)
            else:
                promoter = HttpPromoter(
                    urls[0],
                    canary_window_s=self.config.canary_window_s,
                    canary_poll_s=self.config.canary_poll_s)
        self.promoter = promoter
        # appName out of the variant: the staleness gauge compares the
        # app's ingest high-watermark against the served window.
        ds = (variant.raw.get("datasource") or {}).get("params") or {}
        self.app_name = ds.get("appName")
        # The served generation's watermark, refreshed every cycle (and
        # every trigger poll): the anchor both the staleness gauge and
        # the trigger thresholds measure against.
        self._served_wm = None

    # -- one cycle ----------------------------------------------------------

    def _warm_context(self) -> Optional[WarmStartContext]:
        instances = self.ctx.storage.get_engine_instances()
        prev = instances.get_latest_completed(
            self.engine_id, self.engine_version, self.variant.variant_id)
        if prev is None:
            return None
        wm = data_watermark(prev)
        if wm is None:
            logger.info("previous instance %s has no data watermark "
                        "(pre-refresh generation) — full retrain", prev.id)
            return None
        try:
            models = load_models(self.engine, prev, self.ctx)
        except Exception:
            logger.warning("could not load previous generation %s for "
                           "warm start — full retrain", prev.id,
                           exc_info=True)
            return None
        return WarmStartContext(
            instance=prev, models=models, start_time=wm,
            max_delta_fraction=self.config.max_delta_fraction,
            eval_tolerance=self.config.eval_tolerance)

    def run_once(self) -> Dict[str, Any]:
        """One refresh cycle; returns a summary dict (also published to
        the trace ring as ``refresh.cycle``)."""
        out: Dict[str, Any] = {"promotion": "skipped"}
        t0 = self._clock()
        with obs_trace("refresh.cycle", engine=self.engine_id):
            warm = self._warm_context()
            try:
                instance_id = run_train(
                    self.engine, self.variant, self.ctx,
                    engine_id=self.engine_id,
                    engine_version=self.engine_version,
                    warm_from=warm)
            except TrainPreempted:
                # SIGTERM mid-train: the final checkpoint is written and
                # the CLI owns the exit code — not a failed cycle.
                raise
            except Exception as e:
                # Supervised failure (TrainDiverged, watchdog abort, ...):
                # the cycle records it and the PREVIOUS generation keeps
                # serving — nothing was promoted.
                self.metrics.runs.inc(result="failed")
                logger.error("refresh train failed: %s", e)
                out.update(result="failed", error=str(e)[:200])
                publish_event("refresh.cycle", **out)
                return out
            train_s = self._clock() - t0
            inst = self.ctx.storage.get_engine_instances().get(instance_id)
            mode = (inst.env or {}).get(REFRESH_MODE_KEY, "full") \
                if inst else "full"
            self.metrics.runs.inc(result=mode)
            self.metrics.train_s.set(train_s, mode=mode)
            out.update(result=mode, instance=instance_id,
                       trainS=round(train_s, 3))
            if self.promoter is not None:
                out["promotion"] = self._promote(instance_id)
            self._publish_staleness(inst)
        publish_event("refresh.cycle", **out)
        return out

    def _promote(self, instance_id: str) -> str:
        try:
            self.promoter.promote(instance_id)
        except PromotionRejected as e:
            # The canary gate did its job: candidate rejected, previous
            # generation untouched and still serving.
            self.metrics.promotions.inc(result="rejected")
            logger.warning("promotion rejected: %s", e)
            return "rejected"
        except Exception as e:
            self.metrics.promotions.inc(result="error")
            logger.error("promotion failed: %s", e)
            return "error"
        if self.promoter.canary_window_s > 0:
            verdict = self.promoter.canary_watch()
        else:
            verdict = "promoted"
        self.metrics.promotions.inc(result=verdict)
        return verdict

    def _publish_staleness(self, trained_instance) -> None:
        """Event→servable staleness: ingest high-watermark minus the
        SERVED generation's data watermark.

        With a promoter the served watermark is read back from the
        server itself — a rejected/rolled-back promotion leaves the old
        (staler) watermark serving and the gauge must report THAT, not
        the freshness of an instance nobody serves.  Without a promoter
        the just-trained instance is the newest servable generation and
        anchors the gauge."""
        if not self.app_name:
            return
        if self.promoter is not None:
            try:
                wm = self.promoter.served_watermark()
            except Exception:
                logger.debug("served-watermark probe failed", exc_info=True)
                return
        else:
            wm = data_watermark(trained_instance) \
                if trained_instance is not None else None
        self._served_wm = wm
        self._publish_current_staleness()

    def _publish_current_staleness(self):
        """Staleness vs the last-known served watermark; returns the
        reading (None when either side is unknown).  Trigger mode calls
        this every poll, so the gauge tracks at poll cadence instead of
        once per cycle."""
        if not self.app_name:
            return None
        try:
            latest = self.ctx.event_store.latest_event_time(self.app_name)
        except Exception:
            logger.debug("staleness probe failed", exc_info=True)
            return None
        s = staleness_s(latest, self._served_wm)
        if s is not None:
            self.metrics.staleness.set(s)
        return s

    # -- trigger mode (ISSUE 11 satellite, carried since PR 10) -------------

    def _trigger_mode(self) -> bool:
        return (self.config.trigger_staleness_s is not None
                or self.config.trigger_delta_count is not None)

    def _delta_count(self, cap: int) -> int:
        """Events ingested past the served watermark, counted up to
        ``cap`` (the threshold) — the read never scans further than the
        decision needs."""
        if not self.app_name or self._served_wm is None:
            return 0
        try:
            it = self.ctx.event_store.find(
                self.app_name, start_time=self._served_wm, limit=cap)
            return sum(1 for _ in it)
        except Exception:
            logger.debug("delta-count probe failed", exc_info=True)
            return 0

    def _trigger_ready(self, cycle_started: float):
        """(fire?, reason) — staleness or delta-count threshold crossed,
        or the fixed-cadence backstop elapsed."""
        cfg = self.config
        if self._clock() - cycle_started >= cfg.interval_s:
            return True, "interval"
        if cfg.trigger_staleness_s is not None:
            s = self._publish_current_staleness()
            if s is not None and s >= cfg.trigger_staleness_s:
                return True, "staleness"
        if cfg.trigger_delta_count is not None:
            cap = max(int(cfg.trigger_delta_count), 1)
            if self._delta_count(cap) >= cap:
                return True, "delta_count"
        return False, None

    def _await_trigger(self, sleep: Optional[Callable[[float], None]]
                       ) -> Optional[str]:
        """Poll the trigger conditions until one fires (returns its
        reason) or the daemon is stopped (returns None).  The freshness
        gauges become actuators: a quiet app idles past its cadence-free
        poll loop; a burst of events or a staleness breach fires a cycle
        within one poll tick."""
        from predictionio_tpu.resilience.supervision import (
            preemption_requested,
        )

        started = self._clock()
        poll = max(self.config.trigger_poll_s, 0.01)
        while not self.stop_event.is_set() and not preemption_requested():
            fire, reason = self._trigger_ready(started)
            if fire:
                self.metrics.triggers.inc(reason=reason)
                publish_event("refresh.trigger", reason=reason)
                logger.info("refresh trigger fired: %s", reason)
                return reason
            if sleep is not None:
                sleep(poll)
            elif self.stop_event.wait(poll):
                return None
        return None

    # -- follow mode --------------------------------------------------------

    def follow(self, sleep: Callable[[float], None] = None) -> int:
        """Loop ``run_once`` until :attr:`stop_event` (or a
        SIGTERM-driven preemption request) stops it — on the fixed
        cadence by default, or trigger-driven when a staleness /
        delta-count threshold is configured (the interval then acts as a
        backstop ceiling, never a floor).  Returns the number of
        completed cycles."""
        from predictionio_tpu.resilience.supervision import (
            preemption_requested,
        )

        cycles = 0
        while not self.stop_event.is_set() and not preemption_requested():
            started = self._clock()
            self.run_once()
            cycles += 1
            if self.stop_event.is_set() or preemption_requested():
                break
            if self._trigger_mode():
                if self._await_trigger(sleep) is None:
                    break
                continue
            elapsed = self._clock() - started
            wait = max(self.config.interval_s - elapsed, 0.0)
            if sleep is not None:
                sleep(wait)
            else:
                # Interruptible wait: a SIGTERM between cycles stops the
                # daemon within one poll tick, not one interval.
                if self.stop_event.wait(wait):
                    break
        return cycles

    def stop(self) -> None:
        self.stop_event.set()
