from predictionio_tpu.templates.twotower.engine import (
    DataSourceParams,
    InteractionData,
    ItemScore,
    PredictedResult,
    Query,
    TwoTowerAlgorithm,
    TwoTowerAlgorithmParams,
    TwoTowerDataSource,
    engine,
)

__all__ = [
    "DataSourceParams",
    "InteractionData",
    "ItemScore",
    "PredictedResult",
    "Query",
    "TwoTowerAlgorithm",
    "TwoTowerAlgorithmParams",
    "TwoTowerDataSource",
    "engine",
]
