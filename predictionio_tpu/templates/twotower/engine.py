"""Two-tower retrieval template — neural personal recommendations.

TPU-era engine (BASELINE config 4; absent in the reference — SURVEY.md
§2.2).  Same external contract as the recommendation template so clients
can switch engines without changing queries:

- events: any positive-interaction names (default view/buy/rate)
- query JSON: ``{"user": "u1", "num": 4}``
- result JSON: ``{"itemScores": [{"item", "score"}]}``

Substrate: :mod:`models.two_tower` — in-batch sampled-softmax training,
DP over the ``data`` mesh axis, MIPS top-K serve.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
    WarmStartFallback,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import two_tower as tt_lib
from predictionio_tpu.obs.quality import Scorecard, scorecard_from_matrix
from predictionio_tpu.obs.recall import (
    RecallScorecard,
    build_recall_scorecard,
)
from predictionio_tpu.retrieval import (
    IVFIndex,
    PQCodebook,
    Retriever,
    build_train_index,
    build_train_pq,
    cached_retriever,
    iter_hits,
)

__all__ = [
    "Query", "ItemScore", "PredictedResult", "InteractionData",
    "DataSourceParams", "TwoTowerDataSource", "TwoTowerAlgorithmParams",
    "TwoTowerAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class InteractionData:
    user_ids: np.ndarray
    item_ids: np.ndarray
    user_index: BiMap
    item_index: BiMap


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("view", "buy", "rate")  # noqa: N815


class TwoTowerDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> InteractionData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False, columns=["entity_id", "target_entity_id"])
        from predictionio_tpu.data.columnar import encode_ids

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        return InteractionData(
            user_ids=user_ids,
            item_ids=item_ids,
            user_index=user_index,
            item_index=item_index,
        )


@dataclasses.dataclass(frozen=True)
class TwoTowerAlgorithmParams(Params):
    embedDim: int = 32  # noqa: N815
    hiddenDims: Sequence[int] = (64,)  # noqa: N815
    outDim: int = 32  # noqa: N815
    learningRate: float = 1e-3  # noqa: N815
    temperature: float = 0.05
    batchSize: int = 512  # noqa: N815
    epochs: int = 5
    seed: Optional[int] = None


# eq=False: wrapper identity IS the model generation — keeps the object
# hashable for the weak-keyed retriever cache.
@dataclasses.dataclass(eq=False)
class TwoTowerModelWrapper:
    """Precomputed encoded item corpus + user embeddings for serving.

    ``ivf`` is the optional train-time coarse index (ISSUE 8).  It rides
    INSIDE this pickle, so the staged-reload/rollback generation swap
    moves model and index as one artifact — a rollback can never serve
    generation-N vectors through a generation-N+1 index (the retrieval
    facade's corpus fingerprint check makes any future violation loud).
    """

    user_vecs: np.ndarray   # [U, D] — encoded user representations
    item_vecs: np.ndarray   # [I, D] (L2-normalized tower outputs)
    user_index: BiMap
    item_index: BiMap
    ivf: Optional[IVFIndex] = None
    # Residual PQ codes + codebooks (ISSUE 13): same atomic-swap
    # contract as ``ivf`` — the quantized corpus a generation serves is
    # ALWAYS the one built over its own vectors, fingerprint-pinned.
    pq: Optional[PQCodebook] = None
    # Training-time score-distribution baseline (ISSUE 11): rides the
    # same atomic-swap contract as ``ivf`` — serving drift is always
    # judged against THIS generation's own baseline, fingerprint-pinned
    # to the corpus it was scored over.
    quality: Optional[Scorecard] = None
    # Training-time expected-recall baseline (ISSUE 16): offline
    # recall@k of THIS generation's own ivf/pq structures on a seeded
    # query sample, fingerprint-pinned like ``quality`` — the online
    # recall monitor trips on regression vs this, not an absolute floor.
    recall: Optional[RecallScorecard] = None
    # Warm-start carry (ISSUE 10): the host-numpy train state + the
    # config it was trained under + the interaction count — what the
    # next refresh needs to CONTINUE training on a delta window instead
    # of retraining from scratch.  None on wrappers from older
    # generations (warm_start then falls back to a full retrain).
    train_state: Optional[Dict] = None
    train_cfg: Optional[tt_lib.TwoTowerConfig] = None
    n_examples: int = 0

    def __setstate__(self, d):
        """Old-pickle backfill: wrappers serialized before newer
        optional fields existed (``recall``, …) restore with every
        missing field at its dataclass default."""
        for f in dataclasses.fields(self):
            if f.name not in d and f.default is not dataclasses.MISSING:
                d[f.name] = f.default
        self.__dict__.update(d)

    def retriever(self) -> Retriever:
        """THE serving route to the item corpus (retrieval facade):
        host/device/chunked/sharded/IVF routing, jit caches, metrics —
        one per loaded generation, dying with it."""
        return cached_retriever(self, lambda: Retriever(
            self.item_vecs,
            n_items=len(self.item_index),
            ivf=getattr(self, "ivf", None),
            pq=getattr(self, "pq", None),
            name="twotower"))

    def post_load(self, ctx) -> None:
        """Serving-time re-parallelization: with a serving mesh and a
        corpus above ``PIO_SERVE_SHARD_ABOVE`` items, row-shard the item
        matrix over the ``data`` axis at model-load time so predict
        routes through the mesh-sharded exact rung — per-chip memory and
        score work scale 1/n_chips for corpora that outgrow one chip."""
        mesh = getattr(ctx, "mesh", None)
        if mesh is not None:
            self.retriever().maybe_shard(mesh)


def _merge_index(prev: BiMap, delta: BiMap) -> BiMap:
    """Extend ``prev`` with delta-only keys appended AFTER the existing
    range (existing entities keep their embedding rows; new ones map to
    the grown tail).  Delta keys append in their first-seen order, so
    the merge is deterministic."""
    m = dict(prev.items())
    for k in delta:
        if k not in m:
            m[k] = len(m)
    return BiMap(m)


def _remap_codes(codes: np.ndarray, delta_index: BiMap,
                 merged: BiMap) -> np.ndarray:
    """Delta-local int codes → merged global ids (one vectorized take)."""
    lookup = np.asarray([merged[k] for k in delta_index.to_numpy_keys()],
                        np.int64)
    return lookup[np.asarray(codes, np.int64)]


class TwoTowerAlgorithm(Algorithm):
    params_class = TwoTowerAlgorithmParams

    def _config(self, ctx: RuntimeContext, n_users: int,
                n_items: int) -> tt_lib.TwoTowerConfig:
        p: TwoTowerAlgorithmParams = self.params
        return tt_lib.TwoTowerConfig(
            n_users=n_users,
            n_items=n_items,
            embed_dim=p.embedDim,
            hidden_dims=tuple(p.hiddenDims),
            out_dim=p.outDim,
            learning_rate=p.learningRate,
            temperature=p.temperature,
            batch_size=p.batchSize,
            epochs=p.epochs,
            seed=p.seed if p.seed is not None else ctx.seed,
        )

    def _wrap(self, state: "tt_lib.TwoTowerState",
              cfg: tt_lib.TwoTowerConfig, user_index: BiMap,
              item_index: BiMap, n_examples: int) -> TwoTowerModelWrapper:
        user_vecs = np.asarray(
            tt_lib.encode_users(state.params, jnp.arange(cfg.n_users)))
        item_vecs = np.asarray(
            tt_lib.encode_items(state.params, jnp.arange(cfg.n_items)))
        # Train-time coarse index (policy-gated: PIO_IVF /
        # PIO_IVF_MIN_ITEMS) — the normalized tower outputs are the
        # IVF design target; serialized with the model so the
        # generation swap moves both atomically.
        ivf = build_train_index(item_vecs, name="twotower",
                                seed=cfg.seed)
        # Residual PQ codes (policy-gated: PIO_PQ / PIO_PQ_M /
        # PIO_PQ_MIN_ITEMS), built on top of the IVF coarse structure
        # and swapped with it.
        pq = build_train_pq(item_vecs, name="twotower", ivf=ivf,
                            seed=cfg.seed)
        return TwoTowerModelWrapper(
            user_vecs=user_vecs, item_vecs=item_vecs,
            user_index=user_index,
            item_index=item_index,
            ivf=ivf,
            pq=pq,
            # Quality baseline (ISSUE 11): top-K scores of a seeded user
            # sample against the full corpus — the same population
            # serving emits, so serve-time PSI compares like with like.
            quality=scorecard_from_matrix(user_vecs, item_vecs,
                                          seed=cfg.seed or 0,
                                          name="twotower"),
            # Expected-recall baseline (ISSUE 16): offline recall of the
            # structures just built, through the same search paths and
            # nprobe/rerank formulas serving will use.  None when
            # neither structure was built (exact serving — nothing to
            # monitor).
            recall=build_recall_scorecard(user_vecs, item_vecs, ivf=ivf,
                                          pq=pq, seed=cfg.seed or 0,
                                          name="twotower"),
            train_state=tt_lib.state_to_host(state),
            train_cfg=cfg,
            n_examples=int(n_examples))

    def train(self, ctx: RuntimeContext, prepared_data: InteractionData) -> TwoTowerModelWrapper:
        if len(prepared_data.user_ids) == 0:
            raise ValueError("No interaction events found — check appName.")
        cfg = self._config(ctx, len(prepared_data.user_index),
                           len(prepared_data.item_index))
        state = tt_lib.train(prepared_data.user_ids, prepared_data.item_ids,
                             cfg, mesh=ctx.mesh)
        return self._wrap(state, cfg, prepared_data.user_index,
                          prepared_data.item_index,
                          len(prepared_data.user_ids))

    def warm_start(self, ctx: RuntimeContext, prepared_delta: InteractionData,
                   prev_model: TwoTowerModelWrapper,
                   warm: Any) -> TwoTowerModelWrapper:
        """Delta warm-start (ISSUE 10 tentpole): restore the previous
        generation's carried train state, grow the embedding tables for
        entities first seen in the delta window, and CONTINUE training
        on the delta only — riding the same
        ``DevicePrefetcher``/fused-dispatch/supervision loop a full
        train uses.

        Falls back (``WarmStartFallback`` → full retrain in the same
        engine instance) when: the previous wrapper carries no train
        state (older generation), the algorithm config changed (shapes
        or optimizer semantics differ), the delta exceeds
        ``warm.max_delta_fraction`` of the previous corpus, or the
        continued model's loss on a fixed delta sample REGRESSES past
        ``warm.eval_tolerance`` vs the state it started from (a
        divergent continuation must never be promoted on the cheap
        path)."""
        log = logging.getLogger(__name__)
        snapshot = getattr(prev_model, "train_state", None)
        prev_cfg = getattr(prev_model, "train_cfg", None)
        if snapshot is None or prev_cfg is None:
            raise WarmStartFallback(
                "previous generation carries no train state")
        delta_n = len(prepared_delta.user_ids)
        prev_n = int(getattr(prev_model, "n_examples", 0))
        cfg_now = self._config(ctx, prev_cfg.n_users, prev_cfg.n_items)
        for f in ("embed_dim", "hidden_dims", "out_dim", "learning_rate",
                  "temperature", "batch_size", "seed"):
            if getattr(cfg_now, f) != getattr(prev_cfg, f):
                raise WarmStartFallback(
                    f"algorithm config changed ({f}: "
                    f"{getattr(prev_cfg, f)!r} → {getattr(cfg_now, f)!r})")
        max_frac = getattr(warm, "max_delta_fraction", 0.5)
        if prev_n <= 0 or delta_n > max_frac * prev_n:
            raise WarmStartFallback(
                f"delta window too large for continuation "
                f"({delta_n} events vs {prev_n} trained; "
                f"max fraction {max_frac:g})")
        # Merge the delta's entities into the previous index: existing
        # rows keep their ids (and factors); new entities append.
        user_index = _merge_index(prev_model.user_index,
                                  prepared_delta.user_index)
        item_index = _merge_index(prev_model.item_index,
                                  prepared_delta.item_index)
        uids = _remap_codes(prepared_delta.user_ids,
                            prepared_delta.user_index, user_index)
        iids = _remap_codes(prepared_delta.item_ids,
                            prepared_delta.item_index, item_index)
        cfg = dataclasses.replace(prev_cfg, n_users=len(user_index),
                                  n_items=len(item_index),
                                  epochs=self.params.epochs)
        state = tt_lib.grow_state(tt_lib.state_from_host(snapshot), cfg)
        if delta_n == 0:
            # Nothing new: re-land the carried state as a fresh
            # generation (its watermark still advances — staleness is
            # measured against the WINDOW, not the weights).
            return self._wrap(state, cfg, user_index, item_index, prev_n)
        # Regression gate sample: fixed (seeded) subset of the delta,
        # scored before and after continuation at the same temperature.
        rng = np.random.default_rng(cfg.seed)
        sample = rng.choice(delta_n, size=min(delta_n, 1024), replace=False)
        loss_before = tt_lib.eval_loss(state.params, uids[sample],
                                       iids[sample], cfg)
        trained = tt_lib.train(uids, iids, cfg, mesh=ctx.mesh,
                               warm_state=state)
        loss_after = tt_lib.eval_loss(trained.params, uids[sample],
                                      iids[sample], cfg)
        tol = getattr(warm, "eval_tolerance", 0.1)
        if not np.isfinite(loss_after) \
                or loss_after > loss_before * (1.0 + tol) + 1e-9:
            raise WarmStartFallback(
                f"warm-started eval regressed on the delta sample "
                f"({loss_before:.4f} → {loss_after:.4f}, "
                f"tolerance {tol:g})")
        log.info("two_tower warm-start: +%d events (%d new users, %d new "
                 "items), delta-sample loss %.4f → %.4f",
                 delta_n, len(user_index) - len(prev_model.user_index),
                 len(item_index) - len(prev_model.item_index),
                 loss_before, loss_after)
        return self._wrap(trained, cfg, user_index, item_index,
                          prev_n + delta_n)

    def predict(self, model: TwoTowerModelWrapper, query: Query) -> PredictedResult:
        # A batch of one: the facade's host fast path answers a lone
        # client in numpy (a B=1 matmul is orders of magnitude below one
        # device dispatch round-trip) — the same PIO_SERVE_HOST_MACS
        # threshold the ALS template uses, parity-tested.
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: TwoTowerModelWrapper, queries):
        """Vectorized serving path for the continuous-batching scheduler:
        ONE retrieval-facade call for the whole cohort.

        All routing (host fast path, mesh-sharded / chunked device
        scoring, the train-time IVF index, pow2 batch + K-menu compile
        discipline) lives in :mod:`predictionio_tpu.retrieval` — this
        template only maps ids.
        """
        known = [(i, q) for i, q in queries
                 if model.user_index.get(q.user) is not None]
        out = [(i, PredictedResult(itemScores=[])) for i, q in queries
               if model.user_index.get(q.user) is None]
        if not known:
            return out
        num = max(q.num for _, q in known)
        idxs = np.asarray([model.user_index[q.user] for _, q in known])
        scores, ids, _info = model.retriever().topk(
            model.user_vecs[idxs], num)
        inv = model.item_index.inverse
        for row, (i, q) in enumerate(known):
            out.append((i, PredictedResult(itemScores=[
                ItemScore(item=inv[ii], score=ss)
                for ii, ss in iter_hits(scores[row], ids[row], q.num)])))
        return out


def engine() -> Engine:
    return Engine(
        datasource_class=TwoTowerDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"twotower": TwoTowerAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
