"""Two-tower retrieval template — neural personal recommendations.

TPU-era engine (BASELINE config 4; absent in the reference — SURVEY.md
§2.2).  Same external contract as the recommendation template so clients
can switch engines without changing queries:

- events: any positive-interaction names (default view/buy/rate)
- query JSON: ``{"user": "u1", "num": 4}``
- result JSON: ``{"itemScores": [{"item", "score"}]}``

Substrate: :mod:`models.two_tower` — in-batch sampled-softmax training,
DP over the ``data`` mesh axis, MIPS top-K serve.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import two_tower as tt_lib
from predictionio_tpu.ops.topk import top_k_scores

__all__ = [
    "Query", "ItemScore", "PredictedResult", "InteractionData",
    "DataSourceParams", "TwoTowerDataSource", "TwoTowerAlgorithmParams",
    "TwoTowerAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class InteractionData:
    user_ids: np.ndarray
    item_ids: np.ndarray
    user_index: BiMap
    item_index: BiMap


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("view", "buy", "rate")  # noqa: N815


class TwoTowerDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> InteractionData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False, columns=["entity_id", "target_entity_id"])
        from predictionio_tpu.data.columnar import encode_ids

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        return InteractionData(
            user_ids=user_ids,
            item_ids=item_ids,
            user_index=user_index,
            item_index=item_index,
        )


@dataclasses.dataclass(frozen=True)
class TwoTowerAlgorithmParams(Params):
    embedDim: int = 32  # noqa: N815
    hiddenDims: Sequence[int] = (64,)  # noqa: N815
    outDim: int = 32  # noqa: N815
    learningRate: float = 1e-3  # noqa: N815
    temperature: float = 0.05
    batchSize: int = 512  # noqa: N815
    epochs: int = 5
    seed: Optional[int] = None


@dataclasses.dataclass
class TwoTowerModelWrapper:
    """Precomputed encoded item corpus + user embeddings for serving."""

    user_vecs: np.ndarray   # [U, D] — encoded user representations
    item_vecs: np.ndarray   # [I, D]
    user_index: BiMap
    item_index: BiMap


class TwoTowerAlgorithm(Algorithm):
    params_class = TwoTowerAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: InteractionData) -> TwoTowerModelWrapper:
        p: TwoTowerAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError("No interaction events found — check appName.")
        cfg = tt_lib.TwoTowerConfig(
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            embed_dim=p.embedDim,
            hidden_dims=tuple(p.hiddenDims),
            out_dim=p.outDim,
            learning_rate=p.learningRate,
            temperature=p.temperature,
            batch_size=p.batchSize,
            epochs=p.epochs,
            seed=p.seed if p.seed is not None else ctx.seed,
        )
        state = tt_lib.train(prepared_data.user_ids, prepared_data.item_ids,
                             cfg, mesh=ctx.mesh)
        user_vecs = np.asarray(
            tt_lib.encode_users(state.params, jnp.arange(cfg.n_users)))
        item_vecs = np.asarray(
            tt_lib.encode_items(state.params, jnp.arange(cfg.n_items)))
        return TwoTowerModelWrapper(
            user_vecs=user_vecs, item_vecs=item_vecs,
            user_index=prepared_data.user_index,
            item_index=prepared_data.item_index)

    def predict(self, model: TwoTowerModelWrapper, query: Query) -> PredictedResult:
        uidx = model.user_index.get(query.user)
        if uidx is None:
            return PredictedResult(itemScores=[])
        q = jnp.asarray(model.user_vecs[uidx][None, :])
        k = min(query.num, model.item_vecs.shape[0])
        scores, ids = top_k_scores(q, jnp.asarray(model.item_vecs), k)
        scores, ids = jax.device_get((scores, ids))  # ONE host transfer
        inv = model.item_index.inverse
        return PredictedResult(itemScores=[
            ItemScore(item=inv[int(i)], score=float(s))
            for s, i in zip(scores[0], ids[0])])

    def batch_predict(self, model: TwoTowerModelWrapper, queries):
        """Vectorized serving path for the continuous-batching scheduler:
        ONE ``top_k_scores`` dispatch for the whole cohort.

        Batch and K are padded to small menus (powers of two / the ALS
        template's K menu) so the serving frontend's varying batch sizes
        hit a handful of compiled XLA programs instead of compiling per
        distinct shape (SURVEY.md §7).
        """
        known = [(i, q) for i, q in queries
                 if model.user_index.get(q.user) is not None]
        out = [(i, PredictedResult(itemScores=[])) for i, q in queries
               if model.user_index.get(q.user) is None]
        if not known:
            return out
        n_items = model.item_vecs.shape[0]
        num = max(q.num for _, q in known)
        k_menu = (1, 10, 100, 1000)
        k = min(n_items, next((m for m in k_menu if m >= num), num))
        idxs = np.asarray([model.user_index[q.user] for _, q in known])
        qvecs = model.user_vecs[idxs]
        pad = (1 << max(len(idxs) - 1, 0).bit_length()) - len(idxs)
        if pad:
            qvecs = np.concatenate(
                [qvecs, np.zeros((pad, qvecs.shape[1]), qvecs.dtype)])
        scores, ids = top_k_scores(
            jnp.asarray(qvecs), jnp.asarray(model.item_vecs), k)
        scores, ids = jax.device_get((scores, ids))  # ONE host transfer
        inv = model.item_index.inverse
        for row, (i, q) in enumerate(known):
            kk = min(q.num, n_items)
            out.append((i, PredictedResult(itemScores=[
                ItemScore(item=inv[int(ii)], score=float(ss))
                for ss, ii in zip(scores[row][:kk], ids[row][:kk])])))
        return out


def engine() -> Engine:
    return Engine(
        datasource_class=TwoTowerDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"twotower": TwoTowerAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
