"""DLRM / Wide&Deep CTR ranking template.

TPU-era engine (BASELINE config 5; absent in the reference — SURVEY.md
§2.2).  Event contract:

- impression events (default ``impression``): user→item with a ``clicked``
  property (bool/0/1), optional ``dense`` list property (numeric context
  features, e.g. position, hour)
- query JSON: ``{"user": "u1", "items": ["i1","i2"], "dense"?: [...]}``
  → result ``{"itemScores": [{"item", "score"}]}`` — scores are predicted
  CTRs, items ranked by them

Categorical fields: (user id, item id) hashed into fixed vocabularies —
unseen entities at serve time degrade gracefully to shared hash buckets.
Substrate: :mod:`models.dlrm` with expert-sharded embedding tables.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import zlib
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
    WarmStartFallback,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.models import dlrm as dlrm_lib

__all__ = [
    "Query", "ItemScore", "PredictedResult", "CTRData", "DataSourceParams",
    "DLRMDataSource", "DLRMAlgorithmParams", "DLRMAlgorithm", "engine",
]


def _hash(s: str, mod: int) -> int:
    """Stable string→bucket hash (zlib.crc32 is deterministic cross-run)."""
    return zlib.crc32(s.encode()) % mod


@dataclasses.dataclass
class Query:
    user: str
    items: List[str]
    dense: Optional[List[float]] = None


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class CTRData:
    dense: np.ndarray    # [N, n_dense]
    cat: np.ndarray      # [N, 2] — hashed (user, item)
    labels: np.ndarray   # [N]
    n_dense: int


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("impression",)  # noqa: N815
    labelProperty: str = "clicked"  # noqa: N815
    denseProperty: str = "dense"  # noqa: N815
    nDense: int = 4  # noqa: N815 — fixed width; shorter lists zero-padded
    userVocab: int = 65536  # noqa: N815
    itemVocab: int = 65536  # noqa: N815


class DLRMDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> CTRData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False,
            columns=["entity_id", "target_entity_id", "properties_json"])
        from predictionio_tpu.data.columnar import bool_property, encode_ids

        if table.num_rows == 0:
            raise ValueError("No impression events found — check appName.")
        # Hash only the UNIQUE ids (dictionary), then index by dense codes —
        # cost scales with entities, not events.
        user_codes, user_index = encode_ids(table.column("entity_id"))
        item_codes, item_index = encode_ids(table.column("target_entity_id"))
        uhash = np.array([_hash(k, p.userVocab) for k in user_index],
                         np.int64)
        ihash = np.array([_hash(k, p.itemVocab) for k in item_index],
                         np.int64)
        cat = np.stack([uhash[user_codes], ihash[item_codes]], axis=1)
        labels = bool_property(table, p.labelProperty).astype(np.float32)
        # Dense feature lists are the one per-row parse left: JSON arrays
        # have no fixed-width columnar representation in the event schema.
        # Fast substring split for well-formed "key": [..] values; anything
        # unexpected (scalar value, malformed floats) falls back to a real
        # JSON parse for that row — never silently garbage.
        props = table.column("properties_json").to_pylist()
        key = '"%s":' % p.denseProperty
        dense_rows = []
        for pr in props:
            d = []
            if pr and key in pr:
                start = pr.index(key) + len(key)
                rest = pr[start:].lstrip()
                end = rest.find("]")
                if rest.startswith("[") and end > 0:
                    seg = rest[1:end].strip()
                    try:
                        d = ([float(x) for x in seg.split(",")][: p.nDense]
                             if seg else [])
                    except ValueError:
                        d = None
                else:
                    d = None
                if d is None:
                    v = json.loads(pr).get(p.denseProperty) or []
                    d = list(v)[: p.nDense] if isinstance(v, list) else []
            d = list(d) + [0.0] * (p.nDense - len(d))
            dense_rows.append(d)
        return CTRData(
            dense=np.asarray(dense_rows, np.float32),
            cat=cat,
            labels=np.asarray(labels, np.float32),
            n_dense=p.nDense,
        )


@dataclasses.dataclass(frozen=True)
class DLRMAlgorithmParams(Params):
    embedDim: int = 16  # noqa: N815
    bottomMlp: Sequence[int] = (32, 16)  # noqa: N815
    topMlp: Sequence[int] = (32,)  # noqa: N815
    learningRate: float = 0.05  # noqa: N815
    batchSize: int = 512  # noqa: N815
    epochs: int = 3
    userVocab: int = 65536  # noqa: N815 — must match the datasource
    itemVocab: int = 65536  # noqa: N815
    seed: Optional[int] = None


@dataclasses.dataclass
class DLRMModelWrapper:
    state: dlrm_lib.DLRMState
    cfg: dlrm_lib.DLRMConfig
    user_vocab: int
    item_vocab: int
    n_dense: int
    # Warm-start carry (ISSUE 10): the wrapper already holds the full
    # train state, so continuation only needs the corpus size for the
    # delta-fraction gate.  0 on wrappers from older generations.
    n_examples: int = 0


class DLRMAlgorithm(Algorithm):
    params_class = DLRMAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: CTRData) -> DLRMModelWrapper:
        p: DLRMAlgorithmParams = self.params
        cfg = dlrm_lib.DLRMConfig(
            vocab_sizes=(p.userVocab, p.itemVocab),
            n_dense=prepared_data.n_dense,
            embed_dim=p.embedDim,
            bottom_mlp=tuple(p.bottomMlp),
            top_mlp=tuple(p.topMlp),
            learning_rate=p.learningRate,
            batch_size=p.batchSize,
            epochs=p.epochs,
            seed=p.seed if p.seed is not None else ctx.seed,
        )
        state = dlrm_lib.train(prepared_data.dense, prepared_data.cat,
                               prepared_data.labels, cfg, mesh=ctx.mesh)
        return DLRMModelWrapper(state=state, cfg=cfg, user_vocab=p.userVocab,
                                item_vocab=p.itemVocab,
                                n_dense=prepared_data.n_dense,
                                n_examples=len(prepared_data.labels))

    @staticmethod
    def _sample_logloss(model_state, cfg, dense, cat, labels) -> float:
        proba = np.asarray(dlrm_lib.predict_proba(model_state, dense, cat,
                                                  cfg), np.float64)
        p = np.clip(proba, 1e-7, 1.0 - 1e-7)
        y = np.asarray(labels, np.float64)
        return float(-np.mean(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))

    def warm_start(self, ctx: RuntimeContext, prepared_delta: CTRData,
                   prev_model: DLRMModelWrapper, warm: Any) -> DLRMModelWrapper:
        """Delta warm-start (ISSUE 10): DLRM's hashed vocabularies are
        fixed-size, so continuation is just more optimizer steps on the
        delta window from the carried state — unseen entities already
        land in shared hash buckets.  Gates mirror the two-tower
        template: config compatibility, delta fraction, and a log-loss
        regression check on a fixed delta sample."""
        log = logging.getLogger(__name__)
        p: DLRMAlgorithmParams = self.params
        prev_n = int(getattr(prev_model, "n_examples", 0))
        delta_n = len(prepared_delta.labels)
        cfg = prev_model.cfg
        seed_now = p.seed if p.seed is not None else ctx.seed
        if (cfg.vocab_sizes != (p.userVocab, p.itemVocab)
                or cfg.n_dense != prepared_delta.n_dense
                or cfg.embed_dim != p.embedDim
                or cfg.bottom_mlp != tuple(p.bottomMlp)
                or cfg.top_mlp != tuple(p.topMlp)
                or cfg.learning_rate != p.learningRate
                or cfg.batch_size != p.batchSize
                or cfg.seed != seed_now):
            raise WarmStartFallback("algorithm config changed")
        max_frac = getattr(warm, "max_delta_fraction", 0.5)
        if prev_n <= 0 or delta_n > max_frac * prev_n:
            raise WarmStartFallback(
                f"delta window too large for continuation ({delta_n} "
                f"events vs {prev_n} trained; max fraction {max_frac:g})")
        if delta_n == 0:
            return DLRMModelWrapper(state=prev_model.state, cfg=cfg,
                                    user_vocab=p.userVocab,
                                    item_vocab=p.itemVocab,
                                    n_dense=prepared_delta.n_dense,
                                    n_examples=prev_n)
        cfg = dataclasses.replace(cfg, epochs=p.epochs)
        rng = np.random.default_rng(cfg.seed)
        sample = rng.choice(delta_n, size=min(delta_n, 1024), replace=False)
        sd, sc, sy = (prepared_delta.dense[sample],
                      prepared_delta.cat[sample],
                      prepared_delta.labels[sample])
        loss_before = self._sample_logloss(prev_model.state, cfg, sd, sc, sy)
        # Fresh buffers for the continuation: the train loop DONATES its
        # carried state, and prev_model keeps serving (and is the
        # comparison baseline above) — it must never hand over its own
        # arrays on donation-capable backends.
        import jax
        import jax.numpy as jnp

        carried = dlrm_lib.DLRMState(
            params=jax.tree.map(lambda x: jnp.array(x, copy=True),
                                prev_model.state.params),
            opt_state=jax.tree.map(lambda x: jnp.array(x, copy=True),
                                   prev_model.state.opt_state),
            step=jnp.array(prev_model.state.step, copy=True))
        state = dlrm_lib.train(prepared_delta.dense, prepared_delta.cat,
                               prepared_delta.labels, cfg, mesh=ctx.mesh,
                               warm_state=carried)
        loss_after = self._sample_logloss(state, cfg, sd, sc, sy)
        tol = getattr(warm, "eval_tolerance", 0.1)
        if not np.isfinite(loss_after) \
                or loss_after > loss_before * (1.0 + tol) + 1e-9:
            raise WarmStartFallback(
                f"warm-started eval regressed on the delta sample "
                f"({loss_before:.4f} → {loss_after:.4f}, tolerance {tol:g})")
        log.info("dlrm warm-start: +%d events, delta-sample logloss "
                 "%.4f → %.4f", delta_n, loss_before, loss_after)
        return DLRMModelWrapper(state=state, cfg=cfg, user_vocab=p.userVocab,
                                item_vocab=p.itemVocab,
                                n_dense=prepared_delta.n_dense,
                                n_examples=prev_n + delta_n)

    def predict(self, model: DLRMModelWrapper, query: Query) -> PredictedResult:
        if not query.items:
            return PredictedResult(itemScores=[])
        n = len(query.items)
        d = list(query.dense or [])[: model.n_dense]
        d += [0.0] * (model.n_dense - len(d))
        dense = np.tile(np.asarray(d, np.float32)[None, :], (n, 1))
        cat = np.stack([
            np.full(n, _hash(query.user, model.user_vocab), np.int64),
            np.array([_hash(i, model.item_vocab) for i in query.items], np.int64),
        ], axis=1)
        proba = np.asarray(
            dlrm_lib.predict_proba(model.state, dense, cat, model.cfg))
        order = np.argsort(-proba)
        return PredictedResult(itemScores=[
            ItemScore(item=query.items[int(i)], score=float(proba[i]))
            for i in order])


def engine() -> Engine:
    return Engine(
        datasource_class=DLRMDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"dlrm": DLRMAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
