from predictionio_tpu.templates.dlrm.engine import (
    CTRData,
    DataSourceParams,
    DLRMAlgorithm,
    DLRMAlgorithmParams,
    DLRMDataSource,
    PredictedResult,
    Query,
    engine,
)

__all__ = [
    "CTRData",
    "DataSourceParams",
    "DLRMAlgorithm",
    "DLRMAlgorithmParams",
    "DLRMDataSource",
    "PredictedResult",
    "Query",
    "engine",
]
