"""Recommendation template — ALS personal recommendations.

Reference: examples/scala-parallel-recommendation (SURVEY.md §2.2) — the
canonical MLlib-ALS template.  Contract preserved:

- events: ``rate`` (user→item, properties.rating) and ``buy`` (user→item,
  implicit, treated as rating 4.0)
- query JSON: ``{"user": "u1", "num": 4}``
- result JSON: ``{"itemScores": [{"item": "i1", "score": 1.2}, ...]}``
- algorithm params: rank / numIterations / lambda / alpha / implicitPrefs /
  seed — the MLlib ``ALS.train`` knob set

Substrate: :mod:`predictionio_tpu.models.als` (batched XLA normal
equations) instead of Spark MLlib; serving top-K is one MXU matmul +
``lax.top_k`` rather than a JVM loop over ``recommendProducts``.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Preparator,
    RuntimeContext,
    WarmStartFallback,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import als as als_lib
from predictionio_tpu.obs.quality import Scorecard, scorecard_from_matrix
from predictionio_tpu.obs.recall import (
    RecallScorecard,
    build_recall_scorecard,
)
from predictionio_tpu.retrieval import (
    IVFIndex,
    PQCodebook,
    Retriever,
    build_train_index,
    build_train_pq,
    cached_retriever,
    iter_hits,
)

__all__ = [
    "engine",
    "Query",
    "ItemScore",
    "PredictedResult",
    "Ratings",
    "DataSourceParams",
    "RecommendationDataSource",
    "RecommendationPreparator",
    "ALSAlgorithmParams",
    "ALSAlgorithm",
    "ALSModelWrapper",
]


# -- query / result (JSON contract, Appendix A) -----------------------------

@dataclasses.dataclass
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815 — reference JSON field name


# -- training data ----------------------------------------------------------

@dataclasses.dataclass
class Ratings:
    """COO ratings plus the string↔int entity indexes.

    Reference: the template's ``TrainingData(ratings: RDD[Rating])`` — here
    the RDD is columnar numpy destined for device transfer, and the BiMaps
    (reference: ``ALSModel`` members userStringIntMap/itemStringIntMap)
    travel with the data.
    """

    user_ids: np.ndarray
    item_ids: np.ndarray
    ratings: np.ndarray
    user_index: BiMap
    item_index: BiMap
    # Serving fold-in context (ISSUE 10): the trained wrapper needs to
    # know WHERE its events live and how to weigh them so an unseen
    # user's recent events can be solved in at predict time.  Filled by
    # the datasource; defaults keep older pickles/tests loading.
    app_name: Optional[str] = None
    event_names: Sequence[str] = ()
    buy_rating: float = 4.0


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815 — engine.json key parity
    eventNames: Sequence[str] = ("rate", "buy")  # noqa: N815
    buyRating: float = 4.0  # noqa: N815 — implicit "buy" becomes this rating
    evalK: Optional[int] = None  # noqa: N815 — folds for pio eval
    evalQueryNum: int = 10  # noqa: N815
    seed: int = 3


class RecommendationDataSource(DataSource):
    """Reads rate/buy events into COO ratings (reference: DataSource.scala)."""

    params_class = DataSourceParams

    def _read(self, ctx: RuntimeContext) -> Ratings:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName,
            entity_type="user",
            target_entity_type="item",
            event_names=list(p.eventNames),
            # Training is order-independent (the reference's RDD scan is
            # unordered too) and only these four columns feed the COO —
            # both save seconds at the ML-25M shape.
            ordered=False,
            columns=["event", "entity_id", "target_entity_id",
                     "properties_json"],
        )
        # Columnar end-to-end (VERDICT.md round-1 item 4): dictionary-encode
        # ids and regex-extract the rating — Arrow kernels, no Python loop
        # over events.
        from predictionio_tpu.data.columnar import (
            encode_ids, event_mask, numeric_property,
        )

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        is_rate = event_mask(table, ["rate"])
        raw = numeric_property(table, "rating", default=np.nan)
        ratings = np.where(is_rate, raw, p.buyRating).astype(np.float32)
        # Decided semantic (round-2 verdict item 8, PARITY.md): a `rate`
        # event with no numeric `rating` property is DROPPED with a
        # warning — never trained as rating 0.0 (a strong negative signal
        # in explicit ALS).  Upstream's DataSource would throw and fail
        # the whole train; dropping keeps one malformed producer from
        # taking down retraining.
        bad = is_rate & ~np.isfinite(ratings)
        if bad.any():
            import logging

            logging.getLogger(__name__).warning(
                "dropping %d rate event(s) without a numeric 'rating' "
                "property", int(bad.sum()))
            keep = ~bad
            user_ids, item_ids = user_ids[keep], item_ids[keep]
            ratings = ratings[keep]
        return Ratings(
            user_ids=user_ids,
            item_ids=item_ids,
            ratings=ratings,
            user_index=user_index,
            item_index=item_index,
            app_name=p.appName,
            event_names=tuple(p.eventNames),
            buy_rating=p.buyRating,
        )

    def read_training(self, ctx: RuntimeContext) -> Ratings:
        return self._read(ctx)

    def read_eval(self, ctx: RuntimeContext):
        """K-fold split by rating index; queries ask top-N for each user with
        held-out positives as actuals (reference: DataSource.readEval)."""
        p: DataSourceParams = self.params
        if not p.evalK:
            return []
        data = self._read(ctx)
        n = len(data.user_ids)
        rng = np.random.default_rng(p.seed)
        fold_of = rng.integers(0, p.evalK, n)
        folds = []
        for k in range(p.evalK):
            train_sel = fold_of != k
            test_sel = ~train_sel
            td = Ratings(
                user_ids=data.user_ids[train_sel],
                item_ids=data.item_ids[train_sel],
                ratings=data.ratings[train_sel],
                user_index=data.user_index,
                item_index=data.item_index,
            )
            inv_user = data.user_index.inverse
            inv_item = data.item_index.inverse
            qa: Dict[str, set] = {}
            for u, i, r in zip(data.user_ids[test_sel], data.item_ids[test_sel],
                               data.ratings[test_sel]):
                if r > 0:
                    qa.setdefault(inv_user[u], set()).add(inv_item[i])
            queries = [
                (Query(user=u, num=p.evalQueryNum), sorted(actual))
                for u, actual in sorted(qa.items())
            ]
            folds.append((td, None, queries))
        return folds


class RecommendationPreparator(Preparator):
    """Reference: Preparator.scala — identity over the ratings."""

    def prepare(self, ctx: RuntimeContext, training_data: Ratings) -> Ratings:
        return training_data


# -- algorithm --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 10  # noqa: N815 — MLlib knob names
    lambda_: float = 0.01
    alpha: float = 1.0
    implicitPrefs: bool = False  # noqa: N815
    maxDegree: Optional[int] = None  # noqa: N815 — ragged truncation cap
    seed: Optional[int] = None
    # Mesh runs: "auto" row-shards the persistent factor matrices once
    # they exceed the HBM threshold (blocked ALS, SURVEY §2.4 row 2);
    # "replicated"/"sharded" force.  Meshless runs ignore it.
    factorSharding: str = "auto"  # noqa: N815
    # Blocked runs: "auto" windows each HBM chunk's factor gather to the
    # rows it touches (transient ∝ working set, not matrix size);
    # True/False force.  Ignored unless the factors are sharded.
    gatherWindow: Union[bool, str] = "auto"  # noqa: N815


def _fold_in_enabled() -> bool:
    from predictionio_tpu.config import env_bool

    return env_bool(os.environ.get("PIO_FOLD_IN"), True)


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, str(default)) or default)
    except ValueError:
        return default


def _fold_metric():
    from predictionio_tpu.obs import get_registry

    return get_registry().counter(
        "pio_fold_in_total",
        "Serve-time ALS fold-in attempts by outcome "
        "(cached/solved/no_events/unavailable).", ("result",))


# Negative fold-in cache TTL: a user with NO mappable events is cached
# too (an unknown-user query storm must not pay one event-store read —
# a remote RPC on pioserver storage — per request inside the cohort
# dispatch), but only briefly: their first events should become
# recommendations within seconds, not a generation lifetime.
_FOLD_NEG_TTL_S = 30.0


# -- durable shared fold-in cache (ISSUE 15) --------------------------------
#
# N fleet instances each solving the SAME visitor is wasted work and a
# restarted instance re-solves everyone from zero.  Solved factors are
# therefore persisted (best-effort) in the storage layer's shared KV,
# keyed by (factor fingerprint, user): the fingerprint — a SHA-1 of the
# generation's item-factor bytes — identifies the EXACT matrix the solve
# is valid against, so two instances serving the same promoted pickle
# share entries while a rollback/reload to different factors naturally
# misses.  The local per-generation LRU stays the read-through layer;
# the KV is only consulted on an LRU miss.  Entries carry the event-time
# watermark of the newest event they were solved from — a shared hit may
# be staler than a fresh solve would be (documented README caveat; the
# next refresh trains the user in either way).  Negative outcomes are
# never shared: "no events yet" goes stale in seconds.

def _fold_shared_enabled() -> bool:
    from predictionio_tpu.config import env_bool

    return env_bool(os.environ.get("PIO_FOLD_IN_SHARED"), True)


def _fold_encode(vec: np.ndarray, watermark_us: Optional[int]) -> bytes:
    """Header carries the SOLVE time (``ts``, epoch s — the max-age
    gate's anchor: age of the entry, so a re-solve refreshes it) and the
    event-time watermark of the newest event consumed (``wm`` — the
    operator-facing freshness record)."""
    import json as _json
    import time as _time

    v = np.ascontiguousarray(vec, dtype=np.float32)
    head = _json.dumps({"n": int(v.shape[0]), "wm": watermark_us,
                        "ts": round(_time.time(), 3)},
                       separators=(",", ":")).encode()
    return head + b"\n" + v.tobytes()


def _fold_decode(blob: bytes
                 ) -> Optional[Tuple[np.ndarray, Optional[float]]]:
    """(vector, solve-time epoch-s) — the solve time anchors the
    max-age gate."""
    import json as _json

    try:
        head, raw = blob.split(b"\n", 1)
        meta = _json.loads(head)
        vec = np.frombuffer(raw, dtype=np.float32)
        if vec.shape[0] != int(meta["n"]):
            return None
        ts = meta.get("ts")
        return vec.copy(), (float(ts) if ts is not None else None)
    except Exception:
        return None


def _fold_shared_max_age_s() -> float:
    """``PIO_FOLD_IN_SHARED_MAX_AGE_S`` (0 = accept any age): a shared
    entry SOLVED longer ago than this is treated as a MISS so the
    reader re-solves (picking up any events that arrived since).  Anchor
    is the solve time, NOT the user's newest event time — gating on
    event recency would permanently expire every idle user's entry and
    churn re-solves exactly where sharing is safest."""
    try:
        return float(os.environ.get("PIO_FOLD_IN_SHARED_MAX_AGE_S",
                                    "0") or 0)
    except ValueError:
        return 0.0


# eq=False: wrapper identity IS the model generation — keeps the object
# hashable for the weak-keyed retriever cache.
@dataclasses.dataclass(eq=False)
class ALSModelWrapper:
    """Trained factors + indexes (reference: template ALSModel).

    ``ivf`` is the optional train-time coarse index (ISSUE 8) — it rides
    INSIDE this pickle, so the staged-reload/rollback generation swap
    moves model and index as one artifact: a rollback can never serve
    generation-N factors through a generation-N+1 index (the retrieval
    facade's fingerprint check makes any future violation loud).

    Serve-time fold-in (ISSUE 10): an UNSEEN user with recent events
    gets one ridge solve against the frozen item factors
    (``models.als.fold_in``) instead of a cold-start empty result.  The
    folded factor lives in a bounded per-generation LRU — per-process
    and ephemeral by design; the next refresh trains the user in and
    makes it durable.
    """

    model: als_lib.ALSModel
    user_index: BiMap
    item_index: BiMap
    ivf: Optional[IVFIndex] = None
    # Residual PQ codes (ISSUE 13): unlike IVF, safe for these
    # norm-variant factors WITHOUT an opt-in — the exact re-rank
    # re-scores every returned candidate, so quantization error orders
    # a shortlist but never the final top-k.  Same atomic-swap +
    # fingerprint-tripwire contract as ``ivf``.
    pq: Optional[PQCodebook] = None
    # Training-time score-distribution baseline (ISSUE 11): rides the
    # same atomic-swap contract as ``ivf`` — serving drift is judged
    # against THIS generation's own baseline.
    quality: Optional[Scorecard] = None
    # Training-time expected-recall baseline (ISSUE 16): offline
    # recall@k of THIS generation's own ivf/pq structures on a seeded
    # query sample — the online recall monitor trips on regression vs
    # this, never an absolute floor.  None when neither structure was
    # built (exact serving).  Old pickles backfill via __setstate__.
    recall: Optional[RecallScorecard] = None
    # Fold-in context (ISSUE 10), persisted with the generation.
    app_name: Optional[str] = None
    fold_event_names: Sequence[str] = ()
    buy_rating: float = 4.0
    reg: float = 0.01
    alpha: float = 1.0
    # Training-set size of this generation — the warm-start delta
    # fraction gate (ISSUE 17) compares the delta window against it.
    # Old pickles backfill 0 via __setstate__, which makes warm_start
    # decline (prev_n <= 0) rather than guess.
    n_examples: int = 0
    # Host-resident factor copies for the serving fast path: a B=1
    # predict is ~N·K MACs — orders of magnitude below one device
    # dispatch round-trip — so small batches are answered in numpy from
    # these (pulled once, lazily).  None until first host predict.
    _host: Optional[Tuple[np.ndarray, np.ndarray]] = None
    _host_uf: Optional[np.ndarray] = None

    def __post_init__(self):
        self._init_transients()

    def _init_transients(self) -> None:
        # Per-generation serving state — never pickled, dies with the
        # wrapper on reload/rollback (exactly the bounded-cache contract).
        # Values are (vector | None, monotonic-stamp): None is a TTL'd
        # negative entry (user had no usable events at stamp time).
        self._fold_cache: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self._fold_lock = threading.Lock()
        self._event_store = None
        self._yty: Optional[np.ndarray] = None
        # Durable shared cache (ISSUE 15): the KV handle arrives at
        # post_load (the one hook that sees the serving ctx), the
        # fingerprint binds entries to THIS generation's factors.
        self._shared_kv = None
        self._fold_fp: Optional[str] = None
        self._fold_puts = 0

    def __getstate__(self):
        # serving caches are transient (a reloaded model rebuilds them;
        # the per-generation Retriever lives in retrieval.cached_retriever
        # keyed weakly on this object, so it never rides the pickle)
        d = self.__dict__.copy()
        d["_host"] = None
        d["_host_uf"] = None
        for k in ("_fold_cache", "_fold_lock", "_event_store", "_yty",
                  "_shared_kv", "_fold_fp", "_fold_puts"):
            d.pop(k, None)
        return d

    def __setstate__(self, d):
        # Backfill fields a pre-ISSUE-10 pickle lacks, then rebuild the
        # transient serving state.
        for f in dataclasses.fields(self):
            if f.name not in d and f.default is not dataclasses.MISSING:
                d[f.name] = f.default
        self.__dict__.update(d)
        self._init_transients()

    def retriever(self) -> Retriever:
        """THE serving route to the item corpus (retrieval facade):
        host/device/chunked/sharded/IVF routing, jit caches, metrics —
        one per loaded generation, dying with it."""
        # host_fn must hold the wrapper WEAKLY: the retriever is the
        # weak-keyed cache's VALUE, so a strong self capture would pin
        # its own key alive and leak every swapped-out generation.  It
        # is only ever called through a live wrapper's retriever().
        ref = weakref.ref(self)
        return cached_retriever(self, lambda: Retriever(
            self.model.item_factors,
            n_items=len(self.item_index),
            ivf=getattr(self, "ivf", None),
            pq=getattr(self, "pq", None),
            name="als",
            host_fn=lambda: ref().host_factors()[1]))

    def host_user_factors(self) -> np.ndarray:
        """User factors only — batch_predict needs just the query rows;
        pulling host_factors() there would device_get and retain the
        FULL item matrix even when a device rung serves the corpus."""
        if self._host is not None:
            return self._host[0]
        if self._host_uf is None:
            uf = jax.device_get(self.model.user_factors)
            self._host_uf = np.asarray(uf)[: len(self.user_index)]
        return self._host_uf

    def host_factors(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._host is None:
            uf, itf = jax.device_get(
                (self.model.user_factors, self.model.item_factors))
            # a post_load re-shard pads rows to the mesh size; the host
            # copies keep the true extents
            self._host = (uf[:len(self.user_index)],
                          itf[:len(self.item_index)])
        return self._host

    # -- serve-time fold-in (ISSUE 10) ---------------------------------

    def fold_in_user(self, user: str) -> Optional[np.ndarray]:
        """Solve an unseen user's factor from their recent events against
        the frozen item factors; None when fold-in is off, no event
        store is attached (non-serving contexts like eval), or the user
        has no mappable events.  Cached per generation (bounded LRU) so
        repeat visitors never re-solve — the cache dies with the
        wrapper on reload/rollback, exactly when the factors it was
        solved against do."""
        import time as _time

        es = getattr(self, "_event_store", None)
        app = getattr(self, "app_name", None)
        if es is None or not app or not _fold_in_enabled():
            return None
        with self._fold_lock:
            hit = self._fold_cache.get(user)
            if hit is not None:
                vec, t = hit
                if vec is not None or \
                        _time.monotonic() - t < _FOLD_NEG_TTL_S:
                    self._fold_cache.move_to_end(user)
                    _fold_metric().inc(result="cached")
                    return vec
                del self._fold_cache[user]  # expired negative: re-check
        # Shared read-through (ISSUE 15): another instance may already
        # have solved this visitor against the SAME factors — one KV get
        # beats an event-store read plus a ridge solve, and a restarted
        # instance warms from the fleet's work.
        shared_vec = self._fold_shared_get(user)
        if shared_vec is not None:
            self._fold_store(user, shared_vec)
            _fold_metric().inc(result="shared")
            return shared_vec
        from predictionio_tpu.obs import span

        try:
            with span("fold_in", user=user):
                events = es.find_by_entity(
                    app, "user", user,
                    event_names=list(self.fold_event_names) or None,
                    target_entity_type="item",
                    limit=_env_int("PIO_FOLD_IN_EVENTS", 50), latest=True)
        except Exception:
            # A storage blip must degrade to a cold-start answer, never
            # fail the cohort this member rides in.
            logging.getLogger(__name__).debug("fold-in event read failed",
                                              exc_info=True)
            _fold_metric().inc(result="unavailable")
            return None
        ids: List[int] = []
        vals: List[float] = []
        watermark_us: Optional[int] = None
        for ev in events:
            idx = self.item_index.get(ev.target_entity_id)
            if idx is None:
                continue  # item unknown to this generation
            if ev.event == "rate":
                r = ev.properties.get("rating")
                if not isinstance(r, (int, float)) or not np.isfinite(r):
                    continue  # same drop rule as the training read
                vals.append(float(r))
            else:
                vals.append(float(self.buy_rating))
            ids.append(int(idx))
            from predictionio_tpu.data.storage.base import epoch_us

            us = epoch_us(ev.event_time)
            if us is not None and (watermark_us is None
                                   or us > watermark_us):
                watermark_us = us
        if not ids:
            self._fold_store(user, None)
            _fold_metric().inc(result="no_events")
            return None
        _, itf = self.host_factors()
        if self.model.implicit and self._yty is None:
            f = itf.astype(np.float64)
            self._yty = f.T @ f
        vec = als_lib.fold_in(
            itf, np.asarray(ids), np.asarray(vals, np.float32),
            reg=float(getattr(self, "reg", 0.01)),
            alpha=float(getattr(self, "alpha", 1.0)),
            implicit=self.model.implicit, yty=self._yty)
        self._fold_store(user, vec)
        self._fold_shared_put(user, vec, watermark_us)
        _fold_metric().inc(result="solved")
        return vec

    # -- durable shared cache plumbing (ISSUE 15) ----------------------

    def _fold_ns(self) -> str:
        """KV namespace binding entries to THIS generation's factors:
        two instances serving the same promoted pickle hash identical
        bytes and share; different factors (rollback, refresh) miss."""
        if self._fold_fp is None:
            import hashlib

            _, itf = self.host_factors()
            self._fold_fp = hashlib.sha1(
                np.ascontiguousarray(itf, dtype=np.float32).tobytes()
            ).hexdigest()[:16]
        return f"foldin:{self._fold_fp}"

    def _fold_shared_get(self, user: str) -> Optional[np.ndarray]:
        kv = getattr(self, "_shared_kv", None)
        if kv is None or not _fold_shared_enabled():
            return None
        try:
            blob = kv.get(self._fold_ns(), user)
        except Exception:
            # A KV blip must never fail the request — the local solve
            # path below still answers.
            logging.getLogger(__name__).debug(
                "shared fold-in get failed", exc_info=True)
            return None
        if not blob:
            return None
        decoded = _fold_decode(blob)
        if decoded is None:
            return None
        vec, solved_at = decoded
        if vec.shape[0] != self.model.item_factors.shape[-1]:
            return None
        max_age = _fold_shared_max_age_s()
        if max_age > 0 and solved_at is not None:
            import time as _time

            if _time.time() - solved_at > max_age:
                return None  # stale solve: miss → re-solve fresh
        return vec

    def _fold_shared_put(self, user: str, vec: np.ndarray,
                         watermark_us: Optional[int]) -> None:
        """Best-effort write-through; every 256th put prunes the
        namespace to ``PIO_FOLD_IN_SHARED_CAP`` so the shared cache
        stays bounded without any instance owning an eviction thread."""
        kv = getattr(self, "_shared_kv", None)
        if kv is None or not _fold_shared_enabled():
            return
        try:
            ns = self._fold_ns()
            kv.put(ns, user, _fold_encode(vec, watermark_us))
            self._fold_puts += 1
            if self._fold_puts % 256 == 0:
                kv.prune(ns, _env_int("PIO_FOLD_IN_SHARED_CAP", 100_000))
        except Exception:
            logging.getLogger(__name__).debug(
                "shared fold-in put failed", exc_info=True)

    def _fold_store(self, user: str, vec: Optional[np.ndarray]) -> None:
        """Bounded-LRU insert; ``vec=None`` is the (TTL'd) negative
        entry for a user with no usable events."""
        import time as _time

        with self._fold_lock:
            self._fold_cache[user] = (vec, _time.monotonic())
            self._fold_cache.move_to_end(user)
            cap = _env_int("PIO_FOLD_IN_CACHE", 10000)
            while len(self._fold_cache) > max(cap, 1):
                self._fold_cache.popitem(last=False)

    def post_load(self, ctx) -> None:
        """Serving-time re-parallelization (reference: SURVEY §3.2, P
        models re-parallelize in CreateServer): with a serving mesh and
        a corpus above ``PIO_SERVE_SHARD_ABOVE`` items, row-shard the
        item matrix over the ``data`` axis at model-load time — the
        facade's :meth:`~predictionio_tpu.retrieval.Retriever.maybe_shard`
        pads host-side and stages shard-by-shard, and predict then
        routes through the mesh-sharded exact rung (per-chip memory and
        score work scale 1/n_chips).

        Also the fold-in attachment point (ISSUE 10): ``post_load`` is
        the one hook that sees the serving RuntimeContext, so the
        wrapper stashes the event store here — transient, never
        pickled — and ``batch_predict`` can then solve unseen users in
        from their recent events."""
        store = getattr(ctx, "event_store", None)
        if store is not None:
            self._event_store = store
        # Durable fold-in cache (ISSUE 15): stash the shared KV when the
        # serving storage supports it — read-through on LRU misses,
        # write-through after solves.  Unsupported backends (parquetlog)
        # leave it None and fold-in stays LRU-only, exactly as before.
        storage = getattr(ctx, "storage", None)
        if storage is not None and _fold_shared_enabled():
            try:
                self._shared_kv = storage.get_kv()
            except Exception:
                self._shared_kv = None
        mesh = getattr(ctx, "mesh", None)
        if mesh is None:
            return
        r = self.retriever()
        if r.maybe_shard(mesh):
            from jax.sharding import NamedSharding, PartitionSpec as P

            from predictionio_tpu.parallel.mesh import put_sharded

            # Sync the wrapper's reference to the facade's sharded copy
            # so the pre-shard whole-corpus device array can be freed.
            self.model.item_factors = r.vecs
            # queries gather a handful of user rows per request —
            # replicated
            self.model.user_factors = put_sharded(
                np.asarray(jax.device_get(self.model.user_factors)),
                mesh, NamedSharding(mesh, P()))


def _warm_ridge_sweep(target: np.ndarray, frozen: np.ndarray,
                      row_ids: np.ndarray, col_ids: np.ndarray,
                      vals: np.ndarray, *, reg: float, alpha: float,
                      implicit: bool) -> None:
    """One half-sweep of ALS warm-start continuation (ISSUE 17): re-solve
    each delta-touched row of ``target`` against the frozen complement —
    the same normal equation as :func:`models.als.fold_in`, but anchored
    at the row's carried factor (``λn·u_prev`` on the right-hand side)
    so one new event updates a trained row instead of wiping it."""
    order = np.argsort(row_ids, kind="stable")
    rs = row_ids[order]
    cs = col_ids[order]
    vs = vals[order]
    starts = np.flatnonzero(np.r_[True, rs[1:] != rs[:-1]])
    f64 = frozen.astype(np.float64)
    k = f64.shape[1]
    yty = f64.T @ f64 if implicit else None
    bounds = list(starts) + [len(rs)]
    eye = np.eye(k)
    for a, b in zip(bounds[:-1], bounds[1:]):
        row = int(rs[a])
        y = f64[cs[a:b]]
        r = vs[a:b]
        if implicit:
            w = alpha * np.abs(r)
            c = (1.0 + w) * (r > 0)
            mat = yty + (y * w[:, None]).T @ y
            rhs = y.T @ c
        else:
            mat = y.T @ y
            rhs = y.T @ r
        lam = reg * (b - a)
        mat = mat + lam * eye
        rhs = rhs + lam * target[row].astype(np.float64)
        try:
            sol = np.linalg.solve(mat, rhs)
        except np.linalg.LinAlgError:
            sol = np.linalg.lstsq(mat, rhs, rcond=None)[0]
        target[row] = sol.astype(np.float32)


class ALSAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: Ratings) -> ALSModelWrapper:
        p: ALSAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError(
                "No rating events found — check appName/eventNames "
                "(reference template raises the same assertion)."
            )
        cfg = als_lib.ALSConfig(
            rank=p.rank,
            iterations=p.numIterations,
            reg=p.lambda_,
            alpha=p.alpha,
            implicit=p.implicitPrefs,
            max_degree=p.maxDegree,
            seed=p.seed if p.seed is not None else ctx.seed,
            factor_sharding=p.factorSharding,
            gather_window=p.gatherWindow,
        )
        # `pio train --checkpoint-dir D --checkpoint-every N` (or the
        # PIO_CHECKPOINT_* env pair) makes a killed train resume from the
        # last complete sweep, bitwise-equal to an uninterrupted run.
        ck_dir = os.environ.get("PIO_CHECKPOINT_DIR")
        ck_every = int(os.environ.get("PIO_CHECKPOINT_EVERY", "0") or 0)
        model = als_lib.train_als(
            prepared_data.user_ids,
            prepared_data.item_ids,
            prepared_data.ratings,
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            config=cfg,
            mesh=ctx.mesh,
            checkpoint_dir=(os.path.join(ck_dir, "als") if ck_dir else None),
            save_every=ck_every,
        )
        itf_host = np.asarray(
            jax.device_get(model.item_factors))[: len(prepared_data.item_index)]
        uf_host = np.asarray(
            jax.device_get(model.user_factors))[: len(prepared_data.user_index)]
        # Train-time coarse index — serialized with the model so the
        # generation swap moves both atomically.  Raw ALS factors
        # carry popularity-scaled norms (a poor IVF fit: cells
        # partition by direction), so the index builds only under an
        # explicit PIO_IVF=on, never auto.
        ivf_idx = build_train_index(itf_host, name="als", seed=cfg.seed,
                                    require_explicit=True)
        # Residual PQ codes (ISSUE 13): auto-gated like the deep
        # templates — the exact re-rank makes quantization safe for
        # norm-variant factors, so no explicit opt-in is required.
        pq = build_train_pq(itf_host, name="als", ivf=ivf_idx,
                            seed=cfg.seed)
        return ALSModelWrapper(
            model=model,
            user_index=prepared_data.user_index,
            item_index=prepared_data.item_index,
            ivf=ivf_idx,
            pq=pq,
            # Quality baseline (ISSUE 11): top-K reconstruction scores
            # of a seeded user sample against the item factors — the
            # population serving's itemScores come from.
            quality=scorecard_from_matrix(uf_host, itf_host,
                                          seed=cfg.seed or 0, name="als"),
            # Expected-recall baseline (ISSUE 16): offline recall of the
            # structures just built, through the same search paths and
            # nprobe/rerank formulas serving will use.
            recall=build_recall_scorecard(uf_host, itf_host, ivf=ivf_idx,
                                          pq=pq, seed=cfg.seed or 0,
                                          name="als"),
            # Fold-in context (ISSUE 10): where this generation's events
            # live + the solve hyper-parameters it was trained with, so
            # serve-time fold-in solves the SAME normal equation the
            # training sweep would.
            app_name=getattr(prepared_data, "app_name", None),
            fold_event_names=tuple(
                getattr(prepared_data, "event_names", ()) or ()),
            buy_rating=float(getattr(prepared_data, "buy_rating", 4.0)),
            reg=float(p.lambda_),
            alpha=float(p.alpha),
            n_examples=len(prepared_data.ratings),
        )

    def warm_start(self, ctx: RuntimeContext, prepared_delta: Ratings,
                   prev_model: ALSModelWrapper, warm: Any) -> ALSModelWrapper:
        """Delta warm-start (ISSUE 17) — the one refresh rung ALS lacked.

        Factor-init + reduced-sweep retrain: the previous generation's
        factors carry over, delta-new entities get fresh
        normal/sqrt(rank) rows (the :func:`models.als._init_factors`
        scale), and a reduced number of host ridge half-sweeps re-solve
        ONLY the delta-touched rows against the frozen complement,
        anchored at their carried values.  Gates mirror the deep
        templates (DLRM/two-tower): config compatibility, the shared
        delta-fraction gate, and an eval-regression check — RMSE on a
        delta sample restricted to (user, item) pairs the previous
        generation already knew, so before/after is apples-to-apples.
        Any doubt raises :class:`WarmStartFallback` → full retrain.
        """
        log = logging.getLogger(__name__)
        p: ALSAlgorithmParams = self.params
        prev_n = int(getattr(prev_model, "n_examples", 0))
        delta_n = int(len(prepared_delta.ratings))
        if (prev_model.model.rank != p.rank
                or prev_model.model.implicit != p.implicitPrefs
                or float(getattr(prev_model, "reg", p.lambda_))
                != float(p.lambda_)
                or float(getattr(prev_model, "alpha", p.alpha))
                != float(p.alpha)):
            raise WarmStartFallback("algorithm config changed")
        max_frac = getattr(warm, "max_delta_fraction", 0.5)
        if prev_n <= 0 or delta_n > max_frac * prev_n:
            raise WarmStartFallback(
                f"delta window too large for continuation ({delta_n} "
                f"events vs {prev_n} trained; max fraction {max_frac:g})")
        if delta_n == 0:
            # Nothing new: carry the generation forward.  A FRESH wrapper
            # (replace() re-runs __post_init__) because wrapper identity
            # is the serving generation — caches must not be shared.
            return dataclasses.replace(prev_model)
        seed_now = p.seed if p.seed is not None else ctx.seed
        k = int(p.rank)
        uf_prev, itf_prev = prev_model.host_factors()
        # Union-extend the id spaces: previous entities keep their rows,
        # delta-new entities append contiguous fresh indices.
        u_map: Dict[str, int] = dict(prev_model.user_index.items())
        i_map: Dict[str, int] = dict(prev_model.item_index.items())
        for key in prepared_delta.user_index.to_numpy_keys():
            u_map.setdefault(str(key), len(u_map))
        for key in prepared_delta.item_index.to_numpy_keys():
            i_map.setdefault(str(key), len(i_map))
        user_index = BiMap(u_map)
        item_index = BiMap(i_map)
        rng = np.random.default_rng(seed_now if seed_now is not None else 0)
        scale = np.float32(np.sqrt(k))

        def _extend(prev: np.ndarray, n_total: int) -> np.ndarray:
            out = np.array(prev, np.float32, copy=True)
            if n_total <= out.shape[0]:
                return out
            fresh = rng.standard_normal(
                (n_total - out.shape[0], k)).astype(np.float32) / scale
            return np.concatenate([out, fresh], axis=0)

        uf = _extend(uf_prev, len(user_index))
        itf = _extend(itf_prev, len(item_index))
        # Remap delta triplets from the delta read's local indices to the
        # union index space.
        u_lut = np.asarray(
            [u_map[str(kk)]
             for kk in prepared_delta.user_index.to_numpy_keys()], np.int64)
        i_lut = np.asarray(
            [i_map[str(kk)]
             for kk in prepared_delta.item_index.to_numpy_keys()], np.int64)
        rows_u = u_lut[np.asarray(prepared_delta.user_ids, np.int64)]
        rows_i = i_lut[np.asarray(prepared_delta.item_ids, np.int64)]
        vals = np.asarray(prepared_delta.ratings, np.float64)
        # Eval sample: pairs the PREVIOUS generation could already score.
        # All-new-entity deltas have no comparable pairs — the fraction
        # gate above already bounds how much unchecked change they carry.
        known = np.flatnonzero(
            (rows_u < len(prev_model.user_index))
            & (rows_i < len(prev_model.item_index)))
        su = si = sv = None
        if known.size:
            sel = rng.choice(known, size=min(known.size, 1024),
                             replace=False)
            su, si = rows_u[sel], rows_i[sel]
            sv = ((vals[sel] > 0).astype(np.float64)
                  if p.implicitPrefs else vals[sel])

        def _sample_rmse() -> float:
            pred = np.einsum("ij,ij->i", uf[su].astype(np.float64),
                             itf[si].astype(np.float64))
            return float(np.sqrt(np.mean((pred - sv) ** 2)))

        rmse_before = _sample_rmse() if known.size else None
        sweeps = max(1, int(p.numIterations) // 5)
        for _ in range(sweeps):
            _warm_ridge_sweep(uf, itf, rows_u, rows_i, vals,
                              reg=float(p.lambda_), alpha=float(p.alpha),
                              implicit=bool(p.implicitPrefs))
            _warm_ridge_sweep(itf, uf, rows_i, rows_u, vals,
                              reg=float(p.lambda_), alpha=float(p.alpha),
                              implicit=bool(p.implicitPrefs))
        tol = getattr(warm, "eval_tolerance", 0.1)
        if known.size:
            rmse_after = _sample_rmse()
            if not np.isfinite(rmse_after) \
                    or rmse_after > rmse_before * (1.0 + tol) + 1e-9:
                raise WarmStartFallback(
                    f"warm-started eval regressed on the delta sample "
                    f"(rmse {rmse_before:.4f} → {rmse_after:.4f}, "
                    f"tolerance {tol:g})")
            log.info("als warm-start: +%d events (%d sweeps), "
                     "delta-sample rmse %.4f → %.4f", delta_n, sweeps,
                     rmse_before, rmse_after)
        else:
            log.info("als warm-start: +%d events (%d sweeps), all-new "
                     "entities — no comparable eval pairs", delta_n, sweeps)
        import jax.numpy as jnp

        model = als_lib.ALSModel(
            user_factors=jnp.asarray(uf), item_factors=jnp.asarray(itf),
            rank=k, implicit=bool(p.implicitPrefs))
        # Retrieval structures and baselines are derived from THIS
        # generation's factors — rebuild them exactly as train() does;
        # carrying the parent's would mis-route the rows just moved.
        ivf_idx = build_train_index(itf, name="als", seed=seed_now,
                                    require_explicit=True)
        pq = build_train_pq(itf, name="als", ivf=ivf_idx, seed=seed_now)
        return ALSModelWrapper(
            model=model,
            user_index=user_index,
            item_index=item_index,
            ivf=ivf_idx,
            pq=pq,
            quality=scorecard_from_matrix(uf, itf, seed=seed_now or 0,
                                          name="als"),
            recall=build_recall_scorecard(uf, itf, ivf=ivf_idx, pq=pq,
                                          seed=seed_now or 0, name="als"),
            app_name=getattr(prepared_delta, "app_name", None)
            or getattr(prev_model, "app_name", None),
            fold_event_names=tuple(
                getattr(prepared_delta, "event_names", ()) or ())
            or tuple(getattr(prev_model, "fold_event_names", ()) or ()),
            buy_rating=float(getattr(prepared_delta, "buy_rating", 4.0)),
            reg=float(p.lambda_),
            alpha=float(p.alpha),
            n_examples=prev_n + delta_n,
        )

    def predict(self, model: ALSModelWrapper, query: Query) -> PredictedResult:
        # One query = a batch of one: the same facade routing (host MACs
        # threshold, sharded/chunked/IVF device paths) applies, so a
        # corpus that outgrew the host fast path serves B=1 correctly too.
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: ALSModelWrapper, queries):
        """Vectorized eval/serving path — ONE retrieval-facade call for
        the whole cohort.

        All routing (host fast path under ``PIO_SERVE_HOST_MACS``,
        mesh-sharded / chunked device scoring, the train-time IVF index,
        pow2 batch + K-menu compile discipline) lives in
        :mod:`predictionio_tpu.retrieval` — this template only maps ids.

        Unseen users try serve-time fold-in first (ISSUE 10,
        :meth:`ALSModelWrapper.fold_in_user`): a repeat visitor's cached
        (or freshly solved) factor rides the SAME cohort retrieval as
        trained users, so fold-in costs one extra query row, not a
        second dispatch.  Users with no usable events still answer the
        cold-start empty result.
        """
        known = [(i, q) for i, q in queries if q.user in model.user_index]
        rows: List[np.ndarray] = []
        cold: List[Tuple[int, "Query"]] = []
        folded: List[Tuple[int, "Query"]] = []
        for i, q in queries:
            if q.user in model.user_index:
                continue
            vec = model.fold_in_user(q.user)
            if vec is None:
                cold.append((i, q))
            else:
                folded.append((i, q))
                rows.append(vec)
        out = [(i, PredictedResult(itemScores=[])) for i, q in cold]
        answerable = known + folded
        if answerable:
            num = max(q.num for _, q in answerable)
            uf = model.host_user_factors()
            qmat_parts = []
            if known:
                idxs = np.asarray([model.user_index[q.user]
                                   for _, q in known])
                qmat_parts.append(uf[idxs])
            if rows:
                qmat_parts.append(np.stack(rows))
            qmat = np.concatenate(qmat_parts, axis=0) \
                if len(qmat_parts) > 1 else qmat_parts[0]
            scores, ids, _info = model.retriever().topk(qmat, num)
            inv = model.item_index.inverse
            for row, (i, q) in enumerate(answerable):
                out.append((i, PredictedResult(itemScores=[
                    ItemScore(item=inv[ii], score=ss)
                    for ii, ss in iter_hits(scores[row], ids[row], q.num)
                ])))
        return out


def engine() -> Engine:
    """Reference: RecommendationEngine EngineFactory."""
    return Engine(
        datasource_class=RecommendationDataSource,
        preparator_class=RecommendationPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
