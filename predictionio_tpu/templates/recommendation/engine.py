"""Recommendation template — ALS personal recommendations.

Reference: examples/scala-parallel-recommendation (SURVEY.md §2.2) — the
canonical MLlib-ALS template.  Contract preserved:

- events: ``rate`` (user→item, properties.rating) and ``buy`` (user→item,
  implicit, treated as rating 4.0)
- query JSON: ``{"user": "u1", "num": 4}``
- result JSON: ``{"itemScores": [{"item": "i1", "score": 1.2}, ...]}``
- algorithm params: rank / numIterations / lambda / alpha / implicitPrefs /
  seed — the MLlib ``ALS.train`` knob set

Substrate: :mod:`predictionio_tpu.models.als` (batched XLA normal
equations) instead of Spark MLlib; serving top-K is one MXU matmul +
``lax.top_k`` rather than a JVM loop over ``recommendProducts``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    Preparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import als as als_lib
from predictionio_tpu.ops.topk import host_top_k

__all__ = [
    "engine",
    "Query",
    "ItemScore",
    "PredictedResult",
    "Ratings",
    "DataSourceParams",
    "RecommendationDataSource",
    "RecommendationPreparator",
    "ALSAlgorithmParams",
    "ALSAlgorithm",
    "ALSModelWrapper",
]


# -- query / result (JSON contract, Appendix A) -----------------------------

@dataclasses.dataclass
class Query:
    user: str
    num: int = 10


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815 — reference JSON field name


# -- training data ----------------------------------------------------------

@dataclasses.dataclass
class Ratings:
    """COO ratings plus the string↔int entity indexes.

    Reference: the template's ``TrainingData(ratings: RDD[Rating])`` — here
    the RDD is columnar numpy destined for device transfer, and the BiMaps
    (reference: ``ALSModel`` members userStringIntMap/itemStringIntMap)
    travel with the data.
    """

    user_ids: np.ndarray
    item_ids: np.ndarray
    ratings: np.ndarray
    user_index: BiMap
    item_index: BiMap


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815 — engine.json key parity
    eventNames: Sequence[str] = ("rate", "buy")  # noqa: N815
    buyRating: float = 4.0  # noqa: N815 — implicit "buy" becomes this rating
    evalK: Optional[int] = None  # noqa: N815 — folds for pio eval
    evalQueryNum: int = 10  # noqa: N815
    seed: int = 3


class RecommendationDataSource(DataSource):
    """Reads rate/buy events into COO ratings (reference: DataSource.scala)."""

    params_class = DataSourceParams

    def _read(self, ctx: RuntimeContext) -> Ratings:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName,
            entity_type="user",
            target_entity_type="item",
            event_names=list(p.eventNames),
            # Training is order-independent (the reference's RDD scan is
            # unordered too) and only these four columns feed the COO —
            # both save seconds at the ML-25M shape.
            ordered=False,
            columns=["event", "entity_id", "target_entity_id",
                     "properties_json"],
        )
        # Columnar end-to-end (VERDICT.md round-1 item 4): dictionary-encode
        # ids and regex-extract the rating — Arrow kernels, no Python loop
        # over events.
        from predictionio_tpu.data.columnar import (
            encode_ids, event_mask, numeric_property,
        )

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        is_rate = event_mask(table, ["rate"])
        raw = numeric_property(table, "rating", default=np.nan)
        ratings = np.where(is_rate, raw, p.buyRating).astype(np.float32)
        # Decided semantic (round-2 verdict item 8, PARITY.md): a `rate`
        # event with no numeric `rating` property is DROPPED with a
        # warning — never trained as rating 0.0 (a strong negative signal
        # in explicit ALS).  Upstream's DataSource would throw and fail
        # the whole train; dropping keeps one malformed producer from
        # taking down retraining.
        bad = is_rate & ~np.isfinite(ratings)
        if bad.any():
            import logging

            logging.getLogger(__name__).warning(
                "dropping %d rate event(s) without a numeric 'rating' "
                "property", int(bad.sum()))
            keep = ~bad
            user_ids, item_ids = user_ids[keep], item_ids[keep]
            ratings = ratings[keep]
        return Ratings(
            user_ids=user_ids,
            item_ids=item_ids,
            ratings=ratings,
            user_index=user_index,
            item_index=item_index,
        )

    def read_training(self, ctx: RuntimeContext) -> Ratings:
        return self._read(ctx)

    def read_eval(self, ctx: RuntimeContext):
        """K-fold split by rating index; queries ask top-N for each user with
        held-out positives as actuals (reference: DataSource.readEval)."""
        p: DataSourceParams = self.params
        if not p.evalK:
            return []
        data = self._read(ctx)
        n = len(data.user_ids)
        rng = np.random.default_rng(p.seed)
        fold_of = rng.integers(0, p.evalK, n)
        folds = []
        for k in range(p.evalK):
            train_sel = fold_of != k
            test_sel = ~train_sel
            td = Ratings(
                user_ids=data.user_ids[train_sel],
                item_ids=data.item_ids[train_sel],
                ratings=data.ratings[train_sel],
                user_index=data.user_index,
                item_index=data.item_index,
            )
            inv_user = data.user_index.inverse
            inv_item = data.item_index.inverse
            qa: Dict[str, set] = {}
            for u, i, r in zip(data.user_ids[test_sel], data.item_ids[test_sel],
                               data.ratings[test_sel]):
                if r > 0:
                    qa.setdefault(inv_user[u], set()).add(inv_item[i])
            queries = [
                (Query(user=u, num=p.evalQueryNum), sorted(actual))
                for u, actual in sorted(qa.items())
            ]
            folds.append((td, None, queries))
        return folds


class RecommendationPreparator(Preparator):
    """Reference: Preparator.scala — identity over the ratings."""

    def prepare(self, ctx: RuntimeContext, training_data: Ratings) -> Ratings:
        return training_data


# -- algorithm --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 10  # noqa: N815 — MLlib knob names
    lambda_: float = 0.01
    alpha: float = 1.0
    implicitPrefs: bool = False  # noqa: N815
    maxDegree: Optional[int] = None  # noqa: N815 — ragged truncation cap
    seed: Optional[int] = None
    # Mesh runs: "auto" row-shards the persistent factor matrices once
    # they exceed the HBM threshold (blocked ALS, SURVEY §2.4 row 2);
    # "replicated"/"sharded" force.  Meshless runs ignore it.
    factorSharding: str = "auto"  # noqa: N815
    # Blocked runs: "auto" windows each HBM chunk's factor gather to the
    # rows it touches (transient ∝ working set, not matrix size);
    # True/False force.  Ignored unless the factors are sharded.
    gatherWindow: Union[bool, str] = "auto"  # noqa: N815


@dataclasses.dataclass
class ALSModelWrapper:
    """Trained factors + indexes (reference: template ALSModel)."""

    model: als_lib.ALSModel
    user_index: BiMap
    item_index: BiMap
    # Host-resident factor copies for the serving fast path: a B=1
    # predict is ~N·K MACs — orders of magnitude below one device
    # dispatch round-trip — so small batches are answered in numpy from
    # these (pulled once, lazily).  None until first host predict.
    _host: Optional[Tuple[np.ndarray, np.ndarray]] = None
    # (padded item factors, padding-mask bias) for the chunked MIPS path
    # (built once, reused across requests).  None until first chunked
    # predict.
    _chunk_padded: Optional[Tuple[jax.Array, jax.Array]] = None
    # jitted device MIPS callables keyed by (kind, batch, k): the hot
    # path must be ONE cached dispatch — a fresh closure per request
    # would re-trace and pay several eager round-trips instead.
    _mips_jit: Dict[Tuple, Any] = dataclasses.field(default_factory=dict)

    def __getstate__(self):
        # serving caches are transient (jitted callables and padded
        # device copies don't pickle, and a reloaded model rebuilds them)
        d = self.__dict__.copy()
        d["_host"] = None
        d["_chunk_padded"] = None
        d["_mips_jit"] = {}
        return d

    def host_factors(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._host is None:
            uf, itf = jax.device_get(
                (self.model.user_factors, self.model.item_factors))
            # a post_load re-shard pads rows to the mesh size; the host
            # copies keep the true extents
            self._host = (uf[:len(self.user_index)],
                          itf[:len(self.item_index)])
        return self._host

    def post_load(self, ctx) -> None:
        """Serving-time re-parallelization (reference: SURVEY §3.2, P
        models re-parallelize in CreateServer): with a serving mesh and
        a corpus above ``PIO_SERVE_SHARD_ABOVE`` items, row-shard the
        reloaded factors over the ``data`` axis so predict routes
        through ``ops.topk.sharded_top_k`` — per-chip memory and score
        work scale 1/n_chips for corpora that outgrow one chip."""
        mesh = getattr(ctx, "mesh", None)
        if mesh is None:
            return
        from predictionio_tpu.parallel.mesh import AXIS_DATA, put_sharded
        if AXIS_DATA not in mesh.shape:
            return
        above = int(os.environ.get("PIO_SERVE_SHARD_ABOVE", 1_000_000))
        itf = self.model.item_factors
        if itf.shape[0] <= above:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        d = mesh.shape[AXIS_DATA]
        pad = (-itf.shape[0]) % d
        # pad HOST-side: a jnp.pad would stage the full corpus on one
        # device first — OOM at exactly the scale this hook targets;
        # put_sharded device_puts the numpy array shard-by-shard
        itf_h = np.pad(np.asarray(jax.device_get(itf)), ((0, pad), (0, 0)))
        self.model.item_factors = put_sharded(
            itf_h, mesh, NamedSharding(mesh, P(AXIS_DATA, None)))
        # queries gather a handful of user rows per request — replicated
        self.model.user_factors = put_sharded(
            np.asarray(jax.device_get(self.model.user_factors)), mesh,
            NamedSharding(mesh, P()))


# Guards cold-path serving cache builds (padded corpus copy, jit
# compiles): a burst of concurrent first requests on the threaded server
# must not each materialize its own 512 MB+ padded corpus.  One process-
# wide lock — builds are rare (first request per layout) and short
# relative to the HBM spike they prevent.
_serve_cache_lock = threading.Lock()


class ALSAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: Ratings) -> ALSModelWrapper:
        p: ALSAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError(
                "No rating events found — check appName/eventNames "
                "(reference template raises the same assertion)."
            )
        cfg = als_lib.ALSConfig(
            rank=p.rank,
            iterations=p.numIterations,
            reg=p.lambda_,
            alpha=p.alpha,
            implicit=p.implicitPrefs,
            max_degree=p.maxDegree,
            seed=p.seed if p.seed is not None else ctx.seed,
            factor_sharding=p.factorSharding,
            gather_window=p.gatherWindow,
        )
        # `pio train --checkpoint-dir D --checkpoint-every N` (or the
        # PIO_CHECKPOINT_* env pair) makes a killed train resume from the
        # last complete sweep, bitwise-equal to an uninterrupted run.
        ck_dir = os.environ.get("PIO_CHECKPOINT_DIR")
        ck_every = int(os.environ.get("PIO_CHECKPOINT_EVERY", "0") or 0)
        model = als_lib.train_als(
            prepared_data.user_ids,
            prepared_data.item_ids,
            prepared_data.ratings,
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            config=cfg,
            mesh=ctx.mesh,
            checkpoint_dir=(os.path.join(ck_dir, "als") if ck_dir else None),
            save_every=ck_every,
        )
        return ALSModelWrapper(
            model=model,
            user_index=prepared_data.user_index,
            item_index=prepared_data.item_index,
        )

    def predict(self, model: ALSModelWrapper, query: Query) -> PredictedResult:
        # One query = a batch of one: the same host-vs-device routing
        # (MACs threshold, sharded/chunked device paths) applies, so a
        # corpus that outgrew the host fast path serves B=1 correctly too.
        return self.batch_predict(model, [(0, query)])[0][1]

    def _device_top_k(self, model: ALSModelWrapper, idxs, k: int):
        """Device MIPS over the item corpus, one dispatch, shape-stable.

        Routing (SURVEY §7 "serving latency"): a model whose item
        factors are row-sharded on a mesh serves via
        ``ops.topk.sharded_top_k`` (per-shard scoring, O(k·shards·B)
        ICI traffic); an unsharded corpus above
        ``PIO_SERVE_CHUNK_ABOVE`` items scores in ``chunked_top_k``
        slabs so the [B, N] score block never materializes; small
        corpora take the plain one-matmul path.  Batch pads to the
        next power of two so only a handful of XLA programs compile
        (continuous batching with a compiled batch-size menu).
        """
        from jax.sharding import NamedSharding

        from predictionio_tpu.ops.topk import chunked_top_k, sharded_top_k

        b = 1 << (len(idxs) - 1).bit_length()  # next pow2: 1/2/4/8/...
        uidx = jnp.asarray(list(idxs) + [0] * (b - len(idxs)))
        itf = model.model.item_factors
        n_items = len(model.item_index)
        sh = getattr(itf, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.spec and sh.spec[0] \
                and itf.shape[0] % sh.mesh.shape[sh.spec[0]] == 0:
            fn = model._mips_jit.get(("sharded", b, k))
            if fn is None:
                with _serve_cache_lock:
                    fn = model._mips_jit.get(("sharded", b, k))
                    if fn is None:
                        mesh, axis = sh.mesh, sh.spec[0]

                        def _sharded(uf, itf, uidx):
                            return sharded_top_k(mesh, axis, uf[uidx], itf,
                                                 k, n_valid=n_items)

                        fn = jax.jit(_sharded)
                        model._mips_jit[("sharded", b, k)] = fn
            return fn(model.model.user_factors, itf, uidx)
        chunk_above = int(os.environ.get("PIO_SERVE_CHUNK_ABOVE",
                                         2_000_000))
        if n_items > chunk_above:
            from predictionio_tpu.ops.topk import NEG_INF

            chunk = 262_144

            def _stale(c):
                return c is None or c[0].shape[0] != \
                    itf.shape[0] + (-itf.shape[0]) % chunk

            if _stale(model._chunk_padded):
                with _serve_cache_lock:
                    if _stale(model._chunk_padded):
                        pad = (-itf.shape[0]) % chunk
                        itf_p = jnp.pad(itf, ((0, pad), (0, 0))) \
                            if pad else itf
                        # padding-row mask built ONCE with the padded
                        # factors — rebuilding the [N] bias per request
                        # would upload ~8 MB on the serving hot path
                        bias = jnp.where(
                            jnp.arange(itf_p.shape[0]) < n_items,
                            jnp.float32(0.0), NEG_INF)
                        # ONE corpus copy on device: the padded array
                        # serves every path from here (host_factors trims
                        # by len(item_index))
                        model.model.item_factors = itf_p
                        model._chunk_padded = (itf_p, bias)
            itf_p, bias = model._chunk_padded
            fn = model._mips_jit.get(("chunked", b, k))
            if fn is None:
                with _serve_cache_lock:
                    fn = model._mips_jit.get(("chunked", b, k))
                    if fn is None:
                        def _chunked(uf, itf_p, bias, uidx):
                            return chunked_top_k(uf[uidx], itf_p, k,
                                                 chunk=chunk, biases=bias)

                        fn = jax.jit(_chunked)
                        model._mips_jit[("chunked", b, k)] = fn
            return fn(model.model.user_factors, itf_p, bias, uidx)
        return als_lib.recommend(model.model, uidx, k)

    def batch_predict(self, model: ALSModelWrapper, queries):
        """Vectorized eval/serving path: one batched matmul for all queries.

        The user batch is padded to the next power of two and ``num`` to a
        small menu of K values so only a handful of XLA programs ever
        compile (SURVEY.md §7: continuous batching with a few compiled
        batch sizes) — without this, every distinct batch size arriving
        from the serving frontend triggers a fresh compile.
        """
        known = [(i, q) for i, q in queries if q.user in model.user_index]
        out = [(i, PredictedResult(itemScores=[])) for i, q in queries
               if q.user not in model.user_index]
        if known:
            num = max(q.num for _, q in known)
            idxs = [model.user_index[q.user] for _, q in known]
            k_menu = (1, 10, 100, 1000)
            k = min(len(model.item_index),
                    next((m for m in k_menu if m >= num), num))
            # Host when the batch matmul is small (one device dispatch
            # round-trip costs more than ~1e8 host MACs); device for big
            # sweeps (batch eval over the full catalog, 1M+ corpora).
            work = len(idxs) * len(model.item_index) * model.model.rank
            if work <= int(os.environ.get("PIO_SERVE_HOST_MACS", 2 * 10**8)):
                uf, itf = model.host_factors()
                scores, ids = host_top_k(uf[np.asarray(idxs)], itf, k)
            else:
                scores, ids = self._device_top_k(model, idxs, k)
                # ONE host transfer for the whole batch — per-row
                # np.asarray would round-trip the device per request.
                scores, ids = jax.device_get((scores, ids))
            inv = model.item_index.inverse
            for row, (i, q) in enumerate(known):
                out.append((i, PredictedResult(itemScores=[
                    ItemScore(item=inv[int(ii)], score=float(ss))
                    for ss, ii in zip(scores[row][: q.num],
                                      ids[row][: q.num])
                ])))
        return out


def engine() -> Engine:
    """Reference: RecommendationEngine EngineFactory."""
    return Engine(
        datasource_class=RecommendationDataSource,
        preparator_class=RecommendationPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
