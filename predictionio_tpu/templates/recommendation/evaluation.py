"""Recommendation evaluation — Precision@K / Recall@K sweep over rank.

Reference: the recommendation template's Evaluation.scala variants use
ranking metrics over held-out positives via ``pio eval`` (SURVEY.md §3.4);
upstream's MetricEvaluator pattern with OptionAverageMetric (users with no
held-out positives are skipped, not zero-scored).
"""

from __future__ import annotations

from typing import List, Sequence

from predictionio_tpu.controller import (
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    OptionAverageMetric,
)
from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithmParams,
    DataSourceParams,
    PredictedResult,
    Query,
    engine,
)

__all__ = ["PrecisionAtK", "RecallAtK", "RecommendationEvaluation",
           "evaluation", "default_params_generator", "ParamsList"]


class PrecisionAtK(OptionAverageMetric):
    def __init__(self, k: int = 10):
        self.k = k

    def calculate_one(self, query: Query, predicted: PredictedResult,
                      actual: Sequence[str]):
        if not actual:
            return None  # reference: OptionAverageMetric skips empty actuals
        top = [s.item for s in predicted.itemScores[: self.k]]
        if not top:
            return 0.0
        return len(set(top) & set(actual)) / min(self.k, len(top))

    @property
    def header(self) -> str:
        return f"Precision@{self.k}"


class RecallAtK(OptionAverageMetric):
    def __init__(self, k: int = 10):
        self.k = k

    def calculate_one(self, query: Query, predicted: PredictedResult,
                      actual: Sequence[str]):
        if not actual:
            return None
        top = [s.item for s in predicted.itemScores[: self.k]]
        return len(set(top) & set(actual)) / len(actual)

    @property
    def header(self) -> str:
        return f"Recall@{self.k}"


class ParamsList(EngineParamsGenerator):
    def __init__(self, candidates):
        self._candidates = list(candidates)

    @property
    def engine_params_list(self):
        return self._candidates


def default_params_generator(app_name: str = "testapp", eval_k: int = 2,
                             ranks: Sequence[int] = (8, 16),
                             implicit: bool = True,
                             alpha: float = 10.0) -> ParamsList:
    """Candidates sweep rank; implicit by default — ranking metrics are
    meaningless for explicit MF on near-uniform ratings (it fits values,
    not preferences)."""
    ds = DataSourceParams(appName=app_name, evalK=eval_k)
    return ParamsList([
        EngineParams(
            datasource_params=ds,
            algorithms_params=(
                ("als", ALSAlgorithmParams(rank=r, implicitPrefs=implicit,
                                           alpha=alpha)),),
        )
        for r in ranks
    ])


class RecommendationEvaluation(Evaluation):
    def __init__(self, k: int = 10):
        super().__init__(engine=engine(), metric=PrecisionAtK(k),
                         other_metrics=[RecallAtK(k)])


def evaluation() -> RecommendationEvaluation:
    return RecommendationEvaluation()
