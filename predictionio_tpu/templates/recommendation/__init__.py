from predictionio_tpu.templates.recommendation.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    ALSModelWrapper,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    RecommendationDataSource,
    RecommendationPreparator,
    Ratings,
    engine,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "ALSModelWrapper",
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Query",
    "RecommendationDataSource",
    "RecommendationPreparator",
    "Ratings",
    "engine",
]
