"""Similar-product template — "people who viewed X also viewed".

Reference: examples/scala-parallel-similarproduct (SURVEY.md §2.2):
implicit ALS on view events; at query time the candidate items are scored
by **cosine similarity of item factors** against the query items' factors
(summed over multiple query items), with category/white/black-list
filtering.  Contract preserved:

- events: ``view`` (user→item); ``$set`` "item" entities carry
  ``categories`` (list of strings)
- query JSON: ``{"items": ["i1"], "num": 4, "categories"?: [...],
  "whiteList"?: [...], "blackList"?: [...]}``
- result JSON: ``{"itemScores": [{"item": ..., "score": ...}]}``

Substrate: the pairwise-cosine top-K is one normalized matmul on the MXU
(reference: blocked ``productFeatures`` cosine loop, §2.2 table).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import als as als_lib
from predictionio_tpu.retrieval import Retriever, cached_retriever, iter_hits

__all__ = [
    "Query", "ItemScore", "PredictedResult", "ViewData", "DataSourceParams",
    "SimilarProductDataSource", "ALSAlgorithmParams", "ALSAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    items: List[str]
    num: int = 10
    categories: Optional[List[str]] = None
    whiteList: Optional[List[str]] = None  # noqa: N815 — reference JSON keys
    blackList: Optional[List[str]] = None  # noqa: N815


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class ViewData:
    user_ids: np.ndarray
    item_ids: np.ndarray
    user_index: BiMap
    item_index: BiMap
    item_categories: Dict[str, Set[str]]
    # Item-side fold-in context (ISSUE 15): the trained model needs to
    # know where its view events live so an UNKNOWN query item (a brand
    # new product) can be folded in at serve time.  Defaults keep older
    # pickles/tests loading.
    app_name: Optional[str] = None
    event_names: Sequence[str] = ("view",)


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("view",)  # noqa: N815


class SimilarProductDataSource(DataSource):
    """Reference: DataSource.scala — view events + item $set categories."""

    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> ViewData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False, columns=["entity_id", "target_entity_id"])
        from predictionio_tpu.data.columnar import encode_ids

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        # Item categories come from $set aggregation — per-ENTITY state
        # (small), not per-event, so the dict path is fine here.
        props = ctx.event_store.aggregate_properties(p.appName, "item")
        cats: Dict[str, Set[str]] = {}
        for item, pm in props.items():
            c = pm.get("categories")
            if c:
                cats[item] = set(c)
        return ViewData(
            user_ids=user_ids,
            item_ids=item_ids,
            user_index=user_index,
            item_index=item_index,
            item_categories=cats,
            app_name=p.appName,
            event_names=tuple(p.eventNames),
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 10  # noqa: N815
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None


def _item_fold_in_enabled() -> bool:
    # Same kill switch as the user-side fold-in (ISSUE 10/15): one knob
    # turns every serve-time solve off.
    from predictionio_tpu.config import env_bool

    return env_bool(os.environ.get("PIO_FOLD_IN"), True)


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, str(default)) or default)
    except ValueError:
        return default


def _item_fold_metric():
    from predictionio_tpu.obs import get_registry

    return get_registry().counter(
        "pio_fold_in_items_total",
        "Serve-time item-side fold-in attempts by outcome "
        "(cached/solved/no_events/unavailable).", ("result",))


# Negative-entry TTL, same rationale as the user-side cache: a brand-new
# item's first views should fold in within seconds, not be pinned cold
# for the generation's lifetime.
_ITEM_FOLD_NEG_TTL_S = 30.0


# eq=False: wrapper identity IS the model generation (weak-keyed
# retriever cache needs a hashable owner).
@dataclasses.dataclass(eq=False)
class SimilarProductModel:
    item_factors: np.ndarray       # [I, K] L2-normalized
    item_index: BiMap
    item_categories: Dict[str, Set[str]]
    # Item-side fold-in (ISSUE 15, the carried PR-10 rung): a query item
    # UNKNOWN to this generation (a product added after the last
    # refresh) gets one implicit ridge solve against the frozen USER
    # factors from the users who recently viewed it — "similar to this
    # brand-new product" answers instead of staying cold until the next
    # refresh.  Same bounded per-generation cache + PIO_FOLD_IN kill
    # switch as the recommendation template's user-side fold-in; None
    # user_factors (old pickles) disables it.
    user_factors: Optional[np.ndarray] = None   # [U, K] RAW (unnormalized)
    user_index: Optional[BiMap] = None
    app_name: Optional[str] = None
    fold_event_names: Sequence[str] = ("view",)
    reg: float = 0.01
    alpha: float = 1.0

    def __post_init__(self):
        self._init_transients()

    def _init_transients(self) -> None:
        self._fold_cache: "collections.OrderedDict[str, tuple]" = \
            collections.OrderedDict()
        self._fold_lock = threading.Lock()
        self._event_store = None
        self._uty: Optional[np.ndarray] = None   # UᵀU for implicit solves

    def __getstate__(self):
        d = self.__dict__.copy()
        for k in ("_fold_cache", "_fold_lock", "_event_store", "_uty"):
            d.pop(k, None)
        return d

    def __setstate__(self, d):
        # Backfill fields a pre-ISSUE-15 pickle lacks, then rebuild the
        # transient serving state.
        for f in dataclasses.fields(self):
            if f.name not in d and f.default is not dataclasses.MISSING:
                d[f.name] = f.default
        self.__dict__.update(d)
        self._init_transients()

    def retriever(self) -> Retriever:
        """THE serving route to the item corpus (retrieval facade)."""
        return cached_retriever(self, lambda: Retriever(
            self.item_factors, n_items=len(self.item_index),
            name="similarproduct"))

    def post_load(self, ctx) -> None:
        """Fold-in attachment point: stash the serving event store so
        unknown query items can be solved from their recent views."""
        store = getattr(ctx, "event_store", None)
        if store is not None:
            self._event_store = store

    def fold_in_item(self, item: str) -> Optional[np.ndarray]:
        """L2-normalized folded factor for an UNKNOWN item, solved from
        the KNOWN users who recently viewed it; None when fold-in is
        off, no event store/user factors are attached, or the item has
        no usable views.  Bounded per-generation LRU — dies with the
        wrapper on reload/rollback, exactly when the user factors it was
        solved against do."""
        import time as _time

        es = getattr(self, "_event_store", None)
        uf = getattr(self, "user_factors", None)
        uidx = getattr(self, "user_index", None)
        app = getattr(self, "app_name", None)
        if es is None or uf is None or uidx is None or not app \
                or not _item_fold_in_enabled():
            return None
        with self._fold_lock:
            hit = self._fold_cache.get(item)
            if hit is not None:
                vec, t = hit
                if vec is not None or \
                        _time.monotonic() - t < _ITEM_FOLD_NEG_TTL_S:
                    self._fold_cache.move_to_end(item)
                    _item_fold_metric().inc(result="cached")
                    return vec
                del self._fold_cache[item]
        from predictionio_tpu.models import als as _als
        from predictionio_tpu.obs import span

        try:
            with span("fold_in_item", item=item):
                events = es.find(
                    app, entity_type="user", target_entity_type="item",
                    target_entity_id=item,
                    event_names=list(self.fold_event_names) or None,
                    limit=_env_int("PIO_FOLD_IN_EVENTS", 50),
                    reversed=True)
                events = list(events)
        except Exception:
            logging.getLogger(__name__).debug(
                "item fold-in event read failed", exc_info=True)
            _item_fold_metric().inc(result="unavailable")
            return None
        ids = [int(uidx[ev.entity_id]) for ev in events
               if ev.entity_id in uidx]
        if not ids:
            self._fold_store(item, None)
            _item_fold_metric().inc(result="no_events")
            return None
        if self._uty is None:
            f = np.asarray(uf, np.float64)
            self._uty = f.T @ f
        # The item-side normal equation is the user-side one with roles
        # swapped: implicit views (r=1) against the frozen user factors.
        vec = _als.fold_in(
            np.asarray(uf), np.asarray(ids),
            np.ones(len(ids), np.float32),
            reg=float(getattr(self, "reg", 0.01)),
            alpha=float(getattr(self, "alpha", 1.0)),
            implicit=True, yty=self._uty)
        norm = float(np.linalg.norm(vec))
        vec = vec / (norm if norm > 1e-9 else 1.0)  # corpus is normalized
        self._fold_store(item, vec)
        _item_fold_metric().inc(result="solved")
        return vec

    def _fold_store(self, item: str, vec: Optional[np.ndarray]) -> None:
        import time as _time

        with self._fold_lock:
            self._fold_cache[item] = (vec, _time.monotonic())
            self._fold_cache.move_to_end(item)
            cap = _env_int("PIO_FOLD_IN_CACHE", 10000)
            while len(self._fold_cache) > max(cap, 1):
                self._fold_cache.popitem(last=False)


class ALSAlgorithm(Algorithm):
    """Implicit ALS; keeps only normalized item factors (reference parity —
    the similarproduct ALSModel stores productFeatures only)."""

    params_class = ALSAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: ViewData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError("No view events found — check appName/eventNames.")
        cfg = als_lib.ALSConfig(
            rank=p.rank, iterations=p.numIterations, reg=p.lambda_,
            alpha=p.alpha, implicit=True,
            seed=p.seed if p.seed is not None else ctx.seed)
        model = als_lib.train_als(
            prepared_data.user_ids, prepared_data.item_ids, None,
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            config=cfg, mesh=ctx.mesh)
        f = np.asarray(model.item_factors)
        norms = np.linalg.norm(f, axis=1, keepdims=True)
        f = f / np.where(norms < 1e-9, 1.0, norms)
        return SimilarProductModel(
            item_factors=f,
            item_index=prepared_data.item_index,
            item_categories=prepared_data.item_categories,
            # Item-side fold-in context (ISSUE 15): the RAW user factors
            # (fold-in solves in raw factor space; only the corpus is
            # normalized) + where this generation's view events live.
            user_factors=np.asarray(
                model.user_factors)[: len(prepared_data.user_index)],
            user_index=prepared_data.user_index,
            app_name=getattr(prepared_data, "app_name", None),
            fold_event_names=tuple(
                getattr(prepared_data, "event_names", ()) or ("view",)),
            reg=float(p.lambda_),
            alpha=float(p.alpha),
        )

    def predict(self, model: SimilarProductModel, query: Query) -> PredictedResult:
        known = [model.item_index[i] for i in query.items
                 if i in model.item_index]
        # Item-side fold-in (ISSUE 15): a query item this generation has
        # never trained on (a brand-new product with a few views) gets a
        # serve-time folded factor and contributes to the query vector
        # like any trained item, instead of silently dropping out.
        folded: List[np.ndarray] = []
        for i in query.items:
            if i not in model.item_index:
                vec = model.fold_in_item(i)
                if vec is not None:
                    folded.append(vec)
        if not known and not folded:
            return PredictedResult(itemScores=[])
        # Host fast path (cf. recommendation template): factors are
        # host-resident numpy; one matmul row beats a device dispatch
        # round-trip for any single query.
        f = model.item_factors
        parts = []
        if known:
            parts.append(f[np.asarray(known)].sum(axis=0))
        if folded:
            parts.append(np.sum(folded, axis=0))
        q = np.sum(parts, axis=0, keepdims=True) \
            if len(parts) > 1 else parts[0][None, :]  # [1, K]

        n_items = f.shape[0]
        exclude = np.zeros((1, n_items), dtype=bool)
        exclude[0, known] = True  # never return the query items themselves
        inv = model.item_index.inverse
        if query.categories is not None:
            want = set(query.categories)
            for idx in range(n_items):
                cats = model.item_categories.get(inv[idx], set())
                if not (cats & want):
                    exclude[0, idx] = True
        if query.whiteList is not None:
            allowed = {model.item_index[i] for i in query.whiteList
                       if i in model.item_index}
            for idx in range(n_items):
                if idx not in allowed:
                    exclude[0, idx] = True
        if query.blackList:
            for i in query.blackList:
                if i in model.item_index:
                    exclude[0, model.item_index[i]] = True

        # Facade retrieval with the per-request exclude mask: the
        # planner pins exclude-carrying queries to the exact rungs (an
        # excluded id must never cost recall like an unprobed IVF cell).
        scores, ids, _info = model.retriever().topk(q, query.num,
                                                    exclude=exclude)
        return PredictedResult(itemScores=[
            ItemScore(item=inv[i], score=s)
            for i, s in iter_hits(scores[0], ids[0], query.num)])


def engine() -> Engine:
    """Reference: SimilarProductEngine EngineFactory."""
    return Engine(
        datasource_class=SimilarProductDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
