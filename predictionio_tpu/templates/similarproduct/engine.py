"""Similar-product template — "people who viewed X also viewed".

Reference: examples/scala-parallel-similarproduct (SURVEY.md §2.2):
implicit ALS on view events; at query time the candidate items are scored
by **cosine similarity of item factors** against the query items' factors
(summed over multiple query items), with category/white/black-list
filtering.  Contract preserved:

- events: ``view`` (user→item); ``$set`` "item" entities carry
  ``categories`` (list of strings)
- query JSON: ``{"items": ["i1"], "num": 4, "categories"?: [...],
  "whiteList"?: [...], "blackList"?: [...]}``
- result JSON: ``{"itemScores": [{"item": ..., "score": ...}]}``

Substrate: the pairwise-cosine top-K is one normalized matmul on the MXU
(reference: blocked ``productFeatures`` cosine loop, §2.2 table).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import als as als_lib
from predictionio_tpu.retrieval import Retriever, cached_retriever, iter_hits

__all__ = [
    "Query", "ItemScore", "PredictedResult", "ViewData", "DataSourceParams",
    "SimilarProductDataSource", "ALSAlgorithmParams", "ALSAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    items: List[str]
    num: int = 10
    categories: Optional[List[str]] = None
    whiteList: Optional[List[str]] = None  # noqa: N815 — reference JSON keys
    blackList: Optional[List[str]] = None  # noqa: N815


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class ViewData:
    user_ids: np.ndarray
    item_ids: np.ndarray
    user_index: BiMap
    item_index: BiMap
    item_categories: Dict[str, Set[str]]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("view",)  # noqa: N815


class SimilarProductDataSource(DataSource):
    """Reference: DataSource.scala — view events + item $set categories."""

    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> ViewData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False, columns=["entity_id", "target_entity_id"])
        from predictionio_tpu.data.columnar import encode_ids

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        # Item categories come from $set aggregation — per-ENTITY state
        # (small), not per-event, so the dict path is fine here.
        props = ctx.event_store.aggregate_properties(p.appName, "item")
        cats: Dict[str, Set[str]] = {}
        for item, pm in props.items():
            c = pm.get("categories")
            if c:
                cats[item] = set(c)
        return ViewData(
            user_ids=user_ids,
            item_ids=item_ids,
            user_index=user_index,
            item_index=item_index,
            item_categories=cats,
        )


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    numIterations: int = 10  # noqa: N815
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None


# eq=False: wrapper identity IS the model generation (weak-keyed
# retriever cache needs a hashable owner).
@dataclasses.dataclass(eq=False)
class SimilarProductModel:
    item_factors: np.ndarray       # [I, K] L2-normalized
    item_index: BiMap
    item_categories: Dict[str, Set[str]]

    def retriever(self) -> Retriever:
        """THE serving route to the item corpus (retrieval facade)."""
        return cached_retriever(self, lambda: Retriever(
            self.item_factors, n_items=len(self.item_index),
            name="similarproduct"))


class ALSAlgorithm(Algorithm):
    """Implicit ALS; keeps only normalized item factors (reference parity —
    the similarproduct ALSModel stores productFeatures only)."""

    params_class = ALSAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: ViewData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError("No view events found — check appName/eventNames.")
        cfg = als_lib.ALSConfig(
            rank=p.rank, iterations=p.numIterations, reg=p.lambda_,
            alpha=p.alpha, implicit=True,
            seed=p.seed if p.seed is not None else ctx.seed)
        model = als_lib.train_als(
            prepared_data.user_ids, prepared_data.item_ids, None,
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            config=cfg, mesh=ctx.mesh)
        f = np.asarray(model.item_factors)
        norms = np.linalg.norm(f, axis=1, keepdims=True)
        f = f / np.where(norms < 1e-9, 1.0, norms)
        return SimilarProductModel(
            item_factors=f,
            item_index=prepared_data.item_index,
            item_categories=prepared_data.item_categories,
        )

    def predict(self, model: SimilarProductModel, query: Query) -> PredictedResult:
        known = [model.item_index[i] for i in query.items
                 if i in model.item_index]
        if not known:
            return PredictedResult(itemScores=[])
        # Host fast path (cf. recommendation template): factors are
        # host-resident numpy; one matmul row beats a device dispatch
        # round-trip for any single query.
        f = model.item_factors
        q = f[np.asarray(known)].sum(axis=0, keepdims=True)  # [1, K]

        n_items = f.shape[0]
        exclude = np.zeros((1, n_items), dtype=bool)
        exclude[0, known] = True  # never return the query items themselves
        inv = model.item_index.inverse
        if query.categories is not None:
            want = set(query.categories)
            for idx in range(n_items):
                cats = model.item_categories.get(inv[idx], set())
                if not (cats & want):
                    exclude[0, idx] = True
        if query.whiteList is not None:
            allowed = {model.item_index[i] for i in query.whiteList
                       if i in model.item_index}
            for idx in range(n_items):
                if idx not in allowed:
                    exclude[0, idx] = True
        if query.blackList:
            for i in query.blackList:
                if i in model.item_index:
                    exclude[0, model.item_index[i]] = True

        # Facade retrieval with the per-request exclude mask: the
        # planner pins exclude-carrying queries to the exact rungs (an
        # excluded id must never cost recall like an unprobed IVF cell).
        scores, ids, _info = model.retriever().topk(q, query.num,
                                                    exclude=exclude)
        return PredictedResult(itemScores=[
            ItemScore(item=inv[i], score=s)
            for i, s in iter_hits(scores[0], ids[0], query.num)])


def engine() -> Engine:
    """Reference: SimilarProductEngine EngineFactory."""
    return Engine(
        datasource_class=SimilarProductDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"als": ALSAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
