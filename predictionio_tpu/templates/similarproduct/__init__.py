from predictionio_tpu.templates.similarproduct.engine import (
    ALSAlgorithm,
    ALSAlgorithmParams,
    DataSourceParams,
    ItemScore,
    PredictedResult,
    Query,
    SimilarProductDataSource,
    ViewData,
    engine,
)

__all__ = [
    "ALSAlgorithm",
    "ALSAlgorithmParams",
    "DataSourceParams",
    "ItemScore",
    "PredictedResult",
    "Query",
    "SimilarProductDataSource",
    "ViewData",
    "engine",
]
