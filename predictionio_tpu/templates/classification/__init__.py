from predictionio_tpu.templates.classification.engine import (
    ClassificationDataSource,
    DataSourceParams,
    LabeledData,
    LRAlgorithm,
    LRAlgorithmParams,
    NaiveBayesAlgorithm,
    NaiveBayesAlgorithmParams,
    PredictedResult,
    Query,
    engine,
)
from predictionio_tpu.templates.classification.evaluation import (
    AccuracyEvaluation,
    default_params_generator,
    evaluation,
)

__all__ = [
    "ClassificationDataSource",
    "DataSourceParams",
    "LabeledData",
    "LRAlgorithm",
    "LRAlgorithmParams",
    "NaiveBayesAlgorithm",
    "NaiveBayesAlgorithmParams",
    "PredictedResult",
    "Query",
    "engine",
    "AccuracyEvaluation",
    "default_params_generator",
    "evaluation",
]
