"""Classification template — attribute → label prediction.

Reference: examples/scala-parallel-classification (SURVEY.md §2.2):
``$set`` events on "user" entities carry numeric attributes (``attr0``,
``attr1``, ``attr2``) and a label property (``plan``); MLlib NaiveBayes or
logistic regression learns label | attrs.  Contract preserved:

- query JSON: ``{"attr0": 2.0, "attr1": 0.0, "attr2": 1.0}``
- result JSON: ``{"label": 2.0}``
- ``$set`` aggregation semantics: latest property value per entity wins
  (the reference's PropertyMap fold — SURVEY.md §7 hard parts)

Substrate: :mod:`models.naive_bayes` (one-pass psum statistics) and
:mod:`models.linear` (fused jit gradient steps) instead of MLlib.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.models import linear as lr_lib
from predictionio_tpu.models import naive_bayes as nb_lib

__all__ = [
    "Query", "PredictedResult", "LabeledData", "DataSourceParams",
    "ClassificationDataSource", "NaiveBayesAlgorithmParams",
    "NaiveBayesAlgorithm", "LRAlgorithmParams", "LRAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    attr0: float = 0.0
    attr1: float = 0.0
    attr2: float = 0.0

    def vector(self, attrs: Sequence[str]) -> np.ndarray:
        return np.array([getattr(self, a, 0.0) for a in attrs], np.float32)


@dataclasses.dataclass
class PredictedResult:
    label: float


@dataclasses.dataclass
class LabeledData:
    """Dense feature matrix + integer labels + the label decode table."""

    x: np.ndarray            # [N, D] float32
    y: np.ndarray            # [N] int64 — indices into `classes`
    classes: np.ndarray      # [C] original label values (float)
    attrs: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    entityType: str = "user"  # noqa: N815
    attrs: Sequence[str] = ("attr0", "attr1", "attr2")
    labelAttr: str = "plan"  # noqa: N815
    evalK: Optional[int] = None  # noqa: N815
    seed: int = 3


class ClassificationDataSource(DataSource):
    """Aggregates ``$set`` properties into (attrs, label) rows.

    Reference: DataSource.scala — ``PEventStore.aggregateProperties`` with
    required fields; entities missing any attr or the label are skipped.
    """

    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> LabeledData:
        p: DataSourceParams = self.params
        required = list(p.attrs) + [p.labelAttr]
        props = ctx.event_store.aggregate_properties(
            p.appName, p.entityType, required=required)
        xs, labels = [], []
        for _entity, pm in sorted(props.items()):
            xs.append([float(pm.get(a)) for a in p.attrs])
            labels.append(float(pm.get(p.labelAttr)))
        if not xs:
            raise ValueError(
                f"No entities with properties {required} found in app "
                f"{p.appName!r} (reference template raises the same).")
        x = np.asarray(xs, np.float32)
        label_arr = np.asarray(labels, np.float32)
        classes = np.unique(label_arr)
        y = np.searchsorted(classes, label_arr)
        return LabeledData(x=x, y=y, classes=classes, attrs=tuple(p.attrs))

    def read_eval(self, ctx: RuntimeContext):
        p: DataSourceParams = self.params
        if not p.evalK:
            return []
        data = self.read_training(ctx)
        rng = np.random.default_rng(p.seed)
        fold_of = rng.integers(0, p.evalK, len(data.y))
        folds = []
        for k in range(p.evalK):
            tr = fold_of != k
            te = ~tr
            td = LabeledData(x=data.x[tr], y=data.y[tr], classes=data.classes,
                             attrs=data.attrs)
            qa = [
                (Query(**{a: float(v) for a, v in zip(data.attrs, row)}),
                 float(data.classes[lbl]))
                for row, lbl in zip(data.x[te], data.y[te])
            ]
            folds.append((td, None, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class NaiveBayesAlgorithmParams(Params):
    lambda_: float = 1.0      # Laplace smoothing (reference NB param "lambda")
    modelType: str = "multinomial"  # noqa: N815 — or "gaussian"


@dataclasses.dataclass
class NBModelWrapper:
    model: nb_lib.NaiveBayesModel
    classes: np.ndarray
    attrs: Tuple[str, ...]


class NaiveBayesAlgorithm(Algorithm):
    params_class = NaiveBayesAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: LabeledData) -> NBModelWrapper:
        p: NaiveBayesAlgorithmParams = self.params
        if p.modelType == "gaussian":
            model = nb_lib.train_gaussian(
                prepared_data.x, prepared_data.y, len(prepared_data.classes),
                mesh=ctx.mesh)
        else:
            model = nb_lib.train_multinomial(
                prepared_data.x, prepared_data.y, len(prepared_data.classes),
                alpha=p.lambda_, mesh=ctx.mesh)
        return NBModelWrapper(model=model, classes=prepared_data.classes,
                              attrs=prepared_data.attrs)

    def predict(self, model: NBModelWrapper, query: Query) -> PredictedResult:
        x = query.vector(model.attrs)[None, :]
        lp = nb_lib.predict_log_proba(model.model, jnp.asarray(x))
        return PredictedResult(label=float(model.classes[int(np.argmax(lp[0]))]))

    def batch_predict(self, model: NBModelWrapper, queries):
        x = np.stack([q.vector(model.attrs) for _, q in queries])
        lp = np.asarray(nb_lib.predict_log_proba(model.model, jnp.asarray(x)))
        best = lp.argmax(axis=1)
        return [(i, PredictedResult(label=float(model.classes[b])))
                for (i, _), b in zip(queries, best)]


@dataclasses.dataclass(frozen=True)
class LRAlgorithmParams(Params):
    regParam: float = 0.0  # noqa: N815 — MLlib knob names
    maxIter: int = 200  # noqa: N815
    stepSize: float = 0.1  # noqa: N815
    seed: int = 0


@dataclasses.dataclass
class LRModelWrapper:
    model: lr_lib.LogisticRegressionModel
    classes: np.ndarray
    attrs: Tuple[str, ...]


class LRAlgorithm(Algorithm):
    params_class = LRAlgorithmParams

    def train(self, ctx: RuntimeContext, prepared_data: LabeledData) -> LRModelWrapper:
        p: LRAlgorithmParams = self.params
        cfg = lr_lib.LogisticRegressionConfig(
            n_classes=len(prepared_data.classes), reg=p.regParam,
            learning_rate=p.stepSize, steps=p.maxIter, seed=p.seed)
        model = lr_lib.train(prepared_data.x, prepared_data.y, cfg, mesh=ctx.mesh)
        return LRModelWrapper(model=model, classes=prepared_data.classes,
                              attrs=prepared_data.attrs)

    def predict(self, model: LRModelWrapper, query: Query) -> PredictedResult:
        x = query.vector(model.attrs)[None, :]
        proba = lr_lib.predict_proba(model.model, jnp.asarray(x))
        return PredictedResult(label=float(model.classes[int(np.argmax(proba[0]))]))

    def batch_predict(self, model: LRModelWrapper, queries):
        x = np.stack([q.vector(model.attrs) for _, q in queries])
        proba = np.asarray(lr_lib.predict_proba(model.model, jnp.asarray(x)))
        best = proba.argmax(axis=1)
        return [(i, PredictedResult(label=float(model.classes[b])))
                for (i, _), b in zip(queries, best)]


def engine() -> Engine:
    """Reference: ClassificationEngine EngineFactory."""
    return Engine(
        datasource_class=ClassificationDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"naive": NaiveBayesAlgorithm, "lr": LRAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
