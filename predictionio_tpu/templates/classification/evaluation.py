"""Classification evaluation — Accuracy sweep over NB smoothing / LR reg.

Reference: the classification template's Evaluation.scala +
EngineParamsGenerator (Accuracy metric, sweep over lambda values), run via
``pio eval`` (SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import Sequence

from predictionio_tpu.controller import (
    AverageMetric,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
)
from predictionio_tpu.templates.classification.engine import (
    DataSourceParams,
    NaiveBayesAlgorithmParams,
    PredictedResult,
    Query,
    engine,
)

__all__ = ["Accuracy", "AccuracyEvaluation", "evaluation",
           "default_params_generator", "ParamsList"]


class Accuracy(AverageMetric):
    """Reference: Accuracy extends AverageMetric — 1.0 on exact label match."""

    def calculate_one(self, query: Query, predicted: PredictedResult,
                      actual: float) -> float:
        return 1.0 if predicted.label == actual else 0.0

    @property
    def header(self) -> str:
        return "Accuracy"


class ParamsList(EngineParamsGenerator):
    def __init__(self, candidates: Sequence[EngineParams]):
        self._candidates = list(candidates)

    @property
    def engine_params_list(self):
        return self._candidates


def default_params_generator(app_name: str = "testapp", eval_k: int = 3,
                             lambdas: Sequence[float] = (0.5, 1.0, 5.0)) -> ParamsList:
    """Reference: EngineParamsList — one candidate per smoothing value."""
    ds = DataSourceParams(appName=app_name, evalK=eval_k)
    return ParamsList([
        EngineParams(
            datasource_params=ds,
            algorithms_params=(("naive", NaiveBayesAlgorithmParams(lambda_=lam)),),
        )
        for lam in lambdas
    ])


class AccuracyEvaluation(Evaluation):
    def __init__(self):
        super().__init__(engine=engine(), metric=Accuracy())


def evaluation() -> AccuracyEvaluation:
    """Factory for `pio eval predictionio_tpu.templates.classification:evaluation ...`."""
    return AccuracyEvaluation()
