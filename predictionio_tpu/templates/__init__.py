"""Official engine templates, rebuilt TPU-native.

Reference: the in-repo template mirrors under ``examples/scala-parallel-*``
(SURVEY.md §2.2) — these are the capability bar.  Each template package
exposes an ``engine()`` factory (the reference's EngineFactory), typed
Params per DASE role, and preserves the template's query/result JSON shape
so existing clients work unchanged.

- :mod:`recommendation`  — ALS personal recommendations (MLlib ALS parity)
- :mod:`classification`  — logreg / naive Bayes attribute classification
- :mod:`similarproduct`  — similar-item retrieval from ALS item factors
- :mod:`ecommerce`       — ALS + business-rule filtering in Serving
- :mod:`twotower`        — neural two-tower retrieval (TPU-era addition)
- :mod:`dlrm`            — CTR ranking with sharded embeddings (TPU-era)
"""
