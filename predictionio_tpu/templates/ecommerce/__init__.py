from predictionio_tpu.templates.ecommerce.engine import (
    DataSourceParams,
    ECommAlgorithm,
    ECommAlgorithmParams,
    ECommerceDataSource,
    ItemScore,
    PredictedResult,
    Query,
    TrainingData,
    engine,
)

__all__ = [
    "DataSourceParams",
    "ECommAlgorithm",
    "ECommAlgorithmParams",
    "ECommerceDataSource",
    "ItemScore",
    "PredictedResult",
    "Query",
    "TrainingData",
    "engine",
]
