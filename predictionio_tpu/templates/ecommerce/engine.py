"""E-commerce recommendation template — ALS + live business rules.

Reference: examples/scala-parallel-ecommercerecommendation (SURVEY.md
§2.2): implicit ALS on view/buy events, but serving applies *realtime*
business rules the recommendation template doesn't have:

- exclude items the user has already seen (``LEventStore.findByEntity`` at
  predict time — the per-request storage round-trip of §3.2)
- exclude globally unavailable items (``$set`` events on a "constraint"
  entity ``unavailableItems`` with an ``items`` list property)
- query-level ``categories`` / ``whiteList`` / ``blackList`` filters
- unknown users fall back to popularity (view-count) ranking — the
  reference returns popular items when the user has no factors

Query/result JSON matches the reference:
``{"user": "u1", "num": 4, "categories"?, "whiteList"?, "blackList"?}`` →
``{"itemScores": [{"item", "score"}]}``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
    RuntimeContext,
)
from predictionio_tpu.controller.params import Params
from predictionio_tpu.data.event import BiMap
from predictionio_tpu.models import als as als_lib
from predictionio_tpu.retrieval import Retriever, cached_retriever, iter_hits

__all__ = [
    "Query", "ItemScore", "PredictedResult", "TrainingData",
    "DataSourceParams", "ECommerceDataSource", "ECommAlgorithmParams",
    "ECommAlgorithm", "engine",
]


@dataclasses.dataclass
class Query:
    user: str
    num: int = 10
    categories: Optional[List[str]] = None
    whiteList: Optional[List[str]] = None  # noqa: N815
    blackList: Optional[List[str]] = None  # noqa: N815


@dataclasses.dataclass
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass
class PredictedResult:
    itemScores: List[ItemScore]  # noqa: N815


@dataclasses.dataclass
class TrainingData:
    user_ids: np.ndarray
    item_ids: np.ndarray
    weights: np.ndarray
    user_index: BiMap
    item_index: BiMap
    item_categories: Dict[str, Set[str]]
    view_counts: np.ndarray  # [I] popularity fallback


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    appName: str  # noqa: N815
    eventNames: Sequence[str] = ("view", "buy")  # noqa: N815
    buyWeight: float = 5.0  # noqa: N815 — buys count more than views


class ECommerceDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        p: DataSourceParams = self.params
        table = ctx.event_store.find_columnar(
            p.appName, entity_type="user", target_entity_type="item",
            event_names=list(p.eventNames),
            ordered=False,
            columns=["event", "entity_id", "target_entity_id"])
        from predictionio_tpu.data.columnar import encode_ids, event_mask

        user_ids, user_index = encode_ids(table.column("entity_id"))
        item_ids, item_index = encode_ids(table.column("target_entity_id"))
        weights = np.where(event_mask(table, ["buy"]), p.buyWeight,
                           1.0).astype(np.float32)
        # Item categories come from $set aggregation — per-ENTITY state
        # (small), so the dict path is fine here.
        props = ctx.event_store.aggregate_properties(p.appName, "item")
        cats: Dict[str, Set[str]] = {}
        for item, pm in props.items():
            c = pm.get("categories")
            if c:
                cats[item] = set(c)
        view_counts = np.bincount(item_ids, weights=weights,
                                  minlength=len(item_index)).astype(np.float32)
        return TrainingData(
            user_ids=user_ids,
            item_ids=item_ids,
            weights=weights,
            user_index=user_index,
            item_index=item_index,
            item_categories=cats,
            view_counts=view_counts,
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    appName: str  # noqa: N815 — serving reads live events from this app
    rank: int = 10
    numIterations: int = 10  # noqa: N815
    lambda_: float = 0.01
    alpha: float = 1.0
    seenEvents: Sequence[str] = ("view", "buy")  # noqa: N815
    unseenOnly: bool = True  # noqa: N815
    seed: Optional[int] = None


# eq=False: wrapper identity IS the model generation (weak-keyed
# retriever cache needs a hashable owner).
@dataclasses.dataclass(eq=False)
class ECommModel:
    user_factors: np.ndarray
    item_factors: np.ndarray
    user_index: BiMap
    item_index: BiMap
    item_categories: Dict[str, Set[str]]
    view_counts: np.ndarray

    def retriever(self) -> Retriever:
        """THE serving route to the item corpus (retrieval facade)."""
        return cached_retriever(self, lambda: Retriever(
            self.item_factors, n_items=len(self.item_index),
            name="ecommerce"))


class ECommAlgorithm(Algorithm):
    params_class = ECommAlgorithmParams

    def __init__(self, params=None):
        super().__init__(params)
        self._ctx: Optional[RuntimeContext] = None

    def train(self, ctx: RuntimeContext, prepared_data: TrainingData) -> ECommModel:
        p: ECommAlgorithmParams = self.params
        if len(prepared_data.user_ids) == 0:
            raise ValueError("No view/buy events found — check appName.")
        self._ctx = ctx
        cfg = als_lib.ALSConfig(
            rank=p.rank, iterations=p.numIterations, reg=p.lambda_,
            alpha=p.alpha, implicit=True,
            seed=p.seed if p.seed is not None else ctx.seed)
        model = als_lib.train_als(
            prepared_data.user_ids, prepared_data.item_ids,
            prepared_data.weights,
            n_users=len(prepared_data.user_index),
            n_items=len(prepared_data.item_index),
            config=cfg, mesh=ctx.mesh)
        return ECommModel(
            user_factors=np.asarray(model.user_factors),
            item_factors=np.asarray(model.item_factors),
            user_index=prepared_data.user_index,
            item_index=prepared_data.item_index,
            item_categories=prepared_data.item_categories,
            view_counts=prepared_data.view_counts,
        )

    # -- realtime lookups (reference: LEventStore at predict time) ---------

    def _event_store(self, ctx: Optional[RuntimeContext]):
        ctx = ctx or self._ctx
        if ctx is None:
            from predictionio_tpu.controller import RuntimeContext as RC

            ctx = self._ctx = RC.create()
        return ctx.event_store

    def _seen_items(self, query: Query) -> Set[str]:
        p: ECommAlgorithmParams = self.params
        if not p.unseenOnly:
            return set()
        try:
            evs = self._event_store(None).find_by_entity(
                p.appName, "user", query.user,
                event_names=list(p.seenEvents), limit=512)
        except Exception:
            return set()
        return {e.target_entity_id for e in evs if e.target_entity_id}

    def _unavailable_items(self) -> Set[str]:
        """Latest $set on constraint/unavailableItems (reference parity)."""
        try:
            evs = self._event_store(None).find_by_entity(
                self.params.appName, "constraint", "unavailableItems",
                event_names=["$set"], limit=1)
        except Exception:
            return set()
        for e in evs:
            items = e.properties.get("items")
            if items:
                return set(items)
        return set()

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        n_items = model.item_factors.shape[0]
        inv = model.item_index.inverse
        exclude = np.zeros((1, n_items), dtype=bool)

        for name in self._seen_items(query) | self._unavailable_items():
            idx = model.item_index.get(name)
            if idx is not None:
                exclude[0, idx] = True
        if query.categories is not None:
            want = set(query.categories)
            for idx in range(n_items):
                if not (model.item_categories.get(inv[idx], set()) & want):
                    exclude[0, idx] = True
        if query.whiteList is not None:
            allowed = {model.item_index[i] for i in query.whiteList
                       if i in model.item_index}
            for idx in range(n_items):
                if idx not in allowed:
                    exclude[0, idx] = True
        if query.blackList:
            for i in query.blackList:
                if i in model.item_index:
                    exclude[0, model.item_index[i]] = True

        uidx = model.user_index.get(query.user)
        if uidx is not None:
            # Facade retrieval with the per-request exclude mask: the
            # planner routes a B=1 predict through its host fast path
            # and pins exclude-carrying queries to the exact rungs.
            scores, ids, _info = model.retriever().topk(
                model.user_factors[uidx][None, :], query.num,
                exclude=exclude)
            pairs = [(s, i) for i, s in iter_hits(scores[0], ids[0],
                                                  query.num)]
        else:
            # Popularity fallback (reference: predictDefault).
            counts = np.where(exclude[0], -np.inf, model.view_counts)
            order = np.argsort(-counts)[: query.num]
            pairs = [(float(counts[i]), int(i)) for i in order
                     if np.isfinite(counts[i])]
        return PredictedResult(
            itemScores=[ItemScore(item=inv[i], score=s) for s, i in pairs])


def engine() -> Engine:
    """Reference: ECommerceRecommendationEngine EngineFactory."""
    return Engine(
        datasource_class=ECommerceDataSource,
        preparator_class=IdentityPreparator,
        algorithm_classes={"ecomm": ECommAlgorithm},
        serving_class=FirstServing,
        query_class=Query,
    )
