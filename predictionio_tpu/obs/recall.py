"""Online retrieval-recall observability (ISSUE 16).

Since ISSUE 8/13 most predict traffic is answered by APPROXIMATE
retrieval rungs (``ivf``, ``ivf_pq``, ``pq_flat``) whose recall was
measured exactly once, offline, at bench time.  A skewed delta-refresh,
a truncated corpus sample, or a mis-tuned ``nprobe``/``rerank`` can rot
recall for days while every latency SLO, score-drift gauge, and shadow
overlap reads green — the results come back fast, well-scored, and
WRONG.  This module closes that hole with the same machinery ISSUE 11
proved for score drift, pointed at the retrieval layer:

- **Shadow exact re-rank sampling.**  The retrieval facade
  (:class:`~predictionio_tpu.retrieval.Retriever`) exposes a
  ``recall_hook``; when armed by :class:`RecallMonitor`, sampled
  approximate-rung requests (the ISSUE-11 shared per-request draw —
  ``Waterfall.sample_u`` under ``PIO_RECALL_SAMPLE``) have their query
  vectors + returned ids captured into a bounded queue (overflow drops
  and counts — the shadow-canary cost model: observability must never
  add serving latency).  An off-thread worker re-scores each capture
  through an EXACT brute-force scan of the SAME generation's staged
  corpus and computes live recall@k.
- **Per-rung recall scorecards.**  Template ``train()`` bakes a
  :class:`RecallScorecard` into the model wrapper next to the ISSUE-11
  quality scorecard: the offline recall of the just-built index/codes
  on a seeded query sample, pinned to the corpus fingerprint.  The
  detector trips on REGRESSION VS THE GENERATION'S OWN BASELINE — an
  IVF index is expected to sit at (say) 0.93, so "recall = 0.93" is
  healthy and "recall = 0.70" is rot, without a magic absolute floor.
- **Miss attribution names the knob.**  Every missed true-top-k item
  on ``ivf_pq`` is classified: was its cell PROBED (the PQ shortlist
  saturated — raise ``PIO_PQ_RERANK``) or not (the probe ring is too
  narrow — widen ``PIO_IVF_NPROBE``)?  ``ivf`` misses are all
  cell-misses by construction (the in-cell scan is exact);
  ``pq_flat`` misses are all shortlist-saturation (every code row is
  scanned).  ``tools/attribute_quality.py`` turns the two gauges into
  the recommendation.
- **Gate-wired.**  :meth:`RecallMonitor.augment_quality` folds a third
  verdict into ``/quality.json``'s promotion gate (after drift and
  shadow divergence) with the same asymmetric hysteresis (trip
  instantly, clear only after a ``PIO_RECALL_RECOVERY_S`` dwell) and
  min-samples cold pass-through — the refresh daemon's canary watch and
  the ISSUE-15 rollout bake already poll ``gate.rollback``, so a
  recall-rotten candidate rolls back through the existing
  ``/admin/rollback`` path with ZERO new daemon logic.
- **Self-disabling below the approximate envelope.**  Tiny corpora
  (below ``PIO_IVF_MIN_ITEMS`` / ``PIO_PQ_MIN_ITEMS``) build no index
  and serve exact; the facade hook only fires on approximate rungs and
  train ships no recall scorecard, so the monitor reads
  reporting-only/insufficient and the gate never acts — there is
  nothing to monitor and nothing trips.

Knobs (prefix ``PIO_RECALL``; kill switch registers ZERO instruments):

====================================  ==================================
``PIO_RECALL``                        kill switch (default on)
``PIO_RECALL_SAMPLE``                 captured slice of approximate-rung
                                      requests on the shared per-request
                                      draw (0.05)
``PIO_RECALL_K``                      recall@k the monitor scores (10)
``PIO_RECALL_QUEUE``                  bounded capture queue; overflow
                                      drops, never blocks (256)
``PIO_RECALL_MAX_ROWS``               query rows re-scored per captured
                                      batch (4)
``PIO_RECALL_FAST_WINDOW``            fast (~minutes) window size (256)
``PIO_RECALL_RESERVOIR``              slow (~generation) Algorithm-R
                                      reservoir size (2048)
``PIO_RECALL_MIN_SAMPLES``            per-window floor below which the
                                      verdict is pass-through (50)
``PIO_RECALL_TOLERANCE``              allowed recall drop vs the
                                      scorecard baseline (0.05)
``PIO_RECALL_RECOVERY_S``             trip-false dwell before the
                                      verdict clears (60)
``PIO_RECALL_GATE``                   recall regression may roll back a
                                      promotion (default on)
====================================  ==================================

``tools/lint_metrics.py`` rule 5 pins the single-owner contract: every
``pio_retrieval_recall*`` family registers in THIS module only, so the
fleet-merge schema has one source of truth.  Numpy and the retrieval
search functions are imported lazily (train-time builders and the
off-thread worker only) — the module stays stdlib-cheap on import.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.config import env_bool
from predictionio_tpu.obs.metrics import get_registry
from predictionio_tpu.obs.waterfall import active_sample_u

logger = logging.getLogger(__name__)

__all__ = [
    "RecallConfig",
    "RecallScorecard",
    "build_recall_scorecard",
    "resolve_recall_scorecard",
    "RecallDetector",
    "RecallMonitor",
    "APPROX_RUNGS",
]

# The rungs whose answers are approximate — the only ones worth
# shadow-re-ranking (every other rung IS the exact answer).
APPROX_RUNGS = ("ivf", "ivf_pq", "pq_flat")

# The ks a train-time scorecard bakes baselines for (RecallConfig.k
# defaults to 10, the serving num the shipped templates see most).
SCORECARD_KS = (1, 10)


def _env_f(env, key: str, default: float) -> float:
    raw = env.get(key)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class RecallConfig:
    """Recall-monitor knobs; :meth:`from_env` is the production
    constructor (same pattern as QualityConfig)."""

    enabled: bool = True
    sample: float = 0.05
    k: int = 10
    queue: int = 256
    max_rows: int = 4
    fast_window: int = 256
    reservoir: int = 2048
    min_samples: int = 50
    tolerance: float = 0.05
    recovery_s: float = 60.0
    gate: bool = True

    @classmethod
    def from_env(cls, env=None) -> "RecallConfig":
        env = os.environ if env is None else env
        return cls(
            enabled=env_bool(env.get("PIO_RECALL"), True),
            sample=min(max(_env_f(env, "PIO_RECALL_SAMPLE", 0.05), 0.0),
                       1.0),
            k=max(1, int(_env_f(env, "PIO_RECALL_K", 10))),
            queue=int(_env_f(env, "PIO_RECALL_QUEUE", 256)),
            max_rows=max(1, int(_env_f(env, "PIO_RECALL_MAX_ROWS", 4))),
            fast_window=int(_env_f(env, "PIO_RECALL_FAST_WINDOW", 256)),
            reservoir=int(_env_f(env, "PIO_RECALL_RESERVOIR", 2048)),
            min_samples=int(_env_f(env, "PIO_RECALL_MIN_SAMPLES", 50)),
            tolerance=_env_f(env, "PIO_RECALL_TOLERANCE", 0.05),
            recovery_s=_env_f(env, "PIO_RECALL_RECOVERY_S", 60.0),
            gate=env_bool(env.get("PIO_RECALL_GATE"), True),
        )


# ==========================================================================
# RecallScorecard: the training-time baseline that rides the wrapper
# ==========================================================================

@dataclasses.dataclass
class RecallScorecard:
    """Expected recall of the generation's OWN approximate structures.

    Serialized inside the model wrapper next to the ISSUE-11 quality
    scorecard, so the staged-reload/rollback swap moves baseline and
    index/codes as ONE artifact — the online monitor can never judge
    generation-N retrieval against generation-M expectations.
    ``fingerprint`` is the ISSUE-8 corpus fingerprint of the item
    vectors the baseline was measured over; a mismatch degrades the
    detector to reporting-only (loud, never blocking)."""

    recall: Dict[str, Dict[int, float]]  # rung -> {k: expected recall@k}
    n_queries: int                       # seeded query sample size
    nprobe: int = 0                      # serving formula at build time
    rerank: int = 0
    fingerprint: Optional[str] = None
    built_at: float = 0.0
    name: str = ""

    def expected(self, rung: str, k: int) -> Optional[float]:
        """Baseline recall@k for ``rung``: exact k when baked, else the
        largest baked k at or below it (recall@k is monotone enough in k
        for a regression tolerance), else the smallest baked k."""
        table = (self.recall or {}).get(rung)
        if not table:
            return None
        if k in table:
            return table[k]
        ks = sorted(table)
        for kk in reversed(ks):
            if kk <= k:
                return table[kk]
        return table[ks[0]]

    def summary(self) -> Dict[str, Any]:
        return {
            "present": True,
            "nQueries": self.n_queries,
            "nprobe": self.nprobe,
            "rerank": self.rerank,
            "builtAt": round(self.built_at, 3),
            "name": self.name,
            "fingerprint": self.fingerprint,
            "recall": {rung: {str(k): round(v, 4)
                              for k, v in sorted(table.items())}
                       for rung, table in sorted(self.recall.items())},
        }


def _serving_nprobe(index, reach: int) -> int:
    """The facade's ``_finish_plan`` nprobe formula — the baseline must
    measure the index at the width serving will actually probe."""
    return min(index.nlist,
               max(index.default_nprobe(), index.min_nprobe_for(reach)))


def _serving_rerank(k: int, n_items: int) -> int:
    """The facade's ``_rerank_count`` formula (``PIO_PQ_RERANK`` else
    4·k, clamped to [k, n_items])."""
    raw = os.environ.get("PIO_PQ_RERANK", "").strip()
    r = 0
    if raw:
        try:
            r = int(raw)
        except ValueError:
            pass
    if r <= 0:
        r = 4 * k
    return min(n_items, max(r, k))


def _exact_topk_ids(host_vecs, queries, k: int, chunk: int = 65536):
    """[B, k] int32 ids of the exact top-k (unordered — set membership
    is all recall needs), chunked so the score transient stays bounded
    at million-item corpora."""
    import numpy as np

    q = np.ascontiguousarray(queries, dtype=np.float32)
    n = host_vecs.shape[0]
    k = min(k, n)
    best_s = np.full((len(q), 0), -np.inf, dtype=np.float32)
    best_i = np.zeros((len(q), 0), dtype=np.int32)
    for s0 in range(0, n, chunk):
        block = (q @ host_vecs[s0:s0 + chunk].T).astype(np.float32)
        ids = np.broadcast_to(
            np.arange(s0, s0 + block.shape[1], dtype=np.int32),
            block.shape)
        ms = np.concatenate([best_s, block], axis=1)
        mi = np.concatenate([best_i, ids], axis=1)
        if ms.shape[1] > k:
            part = np.argpartition(-ms, k - 1, axis=1)[:, :k]
            best_s = np.take_along_axis(ms, part, axis=1)
            best_i = np.take_along_axis(mi, part, axis=1)
        else:
            best_s, best_i = ms, mi
    return best_i


def _recall_of_ids(approx_ids, exact_ids) -> float:
    """|approx ∩ exact| / |exact| for one row (sentinel ids skipped)."""
    truth = {int(i) for i in exact_ids if i >= 0}
    if not truth:
        return 1.0
    got = {int(i) for i in approx_ids if i >= 0}
    return len(truth & got) / len(truth)


def build_recall_scorecard(query_vecs, item_vecs, *, ivf=None, pq=None,
                           sample: int = 128, seed: int = 0,
                           name: str = "") -> Optional[RecallScorecard]:
    """Train-time baseline: offline recall@k of the just-built
    index/codes on a seeded query sample, through the SAME host search
    paths and nprobe/rerank formulas serving uses.

    Returns None when the generation carries no approximate structure
    (tiny corpus below the IVF/PQ thresholds, or both opted off) —
    serving is exact, there is nothing to regress, and the online
    monitor self-disables into reporting-only.  Numpy and the search
    functions import lazily: this only runs inside ``pio train``."""
    if ivf is None and pq is None:
        return None
    import numpy as np

    from predictionio_tpu.retrieval.ivf import (
        corpus_fingerprint,
        search_ivf_host,
    )
    from predictionio_tpu.retrieval.pq import (
        search_ivf_pq_host,
        search_pq_host,
    )

    q = np.asarray(query_vecs)
    it = np.ascontiguousarray(np.asarray(item_vecs), dtype=np.float32)
    if q.ndim != 2 or it.ndim != 2 or not len(q) or not len(it):
        return None
    rng = np.random.default_rng(seed)
    n_sample = min(len(q), max(int(sample), 1))
    qs = np.ascontiguousarray(
        q[rng.choice(len(q), size=n_sample, replace=False)],
        dtype=np.float32)
    n_items = it.shape[0]
    recall: Dict[str, Dict[int, float]] = {}
    nprobe_used = rerank_used = 0
    for k in SCORECARD_KS:
        kk = min(k, n_items)
        exact = _exact_topk_ids(it, qs, kk)
        if ivf is not None:
            nprobe = _serving_nprobe(ivf, kk)
            nprobe_used = max(nprobe_used, nprobe)
            _, ids, _ = search_ivf_host(ivf, it, qs, kk, nprobe)
            recall.setdefault("ivf", {})[k] = float(np.mean(
                [_recall_of_ids(ids[b], exact[b])
                 for b in range(n_sample)]))
        if pq is not None:
            rerank = _serving_rerank(kk, n_items)
            rerank_used = max(rerank_used, rerank)
            _, ids, _ = search_pq_host(pq, it, qs, kk, rerank)
            recall.setdefault("pq_flat", {})[k] = float(np.mean(
                [_recall_of_ids(ids[b], exact[b])
                 for b in range(n_sample)]))
            if ivf is not None:
                nprobe = _serving_nprobe(ivf, rerank)
                _, ids, _ = search_ivf_pq_host(ivf, pq, it, qs, kk,
                                               nprobe, rerank)
                recall.setdefault("ivf_pq", {})[k] = float(np.mean(
                    [_recall_of_ids(ids[b], exact[b])
                     for b in range(n_sample)]))
    sc = RecallScorecard(recall=recall, n_queries=n_sample,
                         nprobe=nprobe_used, rerank=rerank_used,
                         fingerprint=corpus_fingerprint(it),
                         built_at=time.time(), name=name)
    logger.info("recall scorecard for %r: %s (n=%d)", name,
                {r: {k: round(v, 3) for k, v in t.items()}
                 for r, t in recall.items()}, n_sample)
    return sc


def resolve_recall_scorecard(models: Sequence[Any]
                             ) -> Tuple[Optional[RecallScorecard],
                                        Optional[str]]:
    """(scorecard, reporting_reason) for a loaded model set — the same
    fingerprint tripwire as ``resolve_scorecard``: a wrapper whose
    corpus no longer matches the baseline's fingerprint degrades the
    detector to reporting-only with an ERROR, never a gate."""
    for m in models or ():
        sc = getattr(m, "recall", None)
        if not isinstance(sc, RecallScorecard):
            continue
        vecs = getattr(m, "item_vecs", None)
        if sc.fingerprint and vecs is not None:
            try:
                import numpy as np

                from predictionio_tpu.retrieval.ivf import (
                    corpus_fingerprint,
                )

                if corpus_fingerprint(np.ascontiguousarray(
                        np.asarray(vecs), dtype=np.float32)) \
                        != sc.fingerprint:
                    logger.error(
                        "recall scorecard fingerprint mismatch for %r — "
                        "recall monitoring degrades to reporting-only "
                        "(serving continues)", type(m).__name__)
                    return None, "fingerprint_mismatch"
            except Exception:
                logger.warning("recall fingerprint check failed",
                               exc_info=True)
        return sc, None
    return None, "no_scorecard"


# ==========================================================================
# Detector: per-rung fast/slow recall windows with hysteresis
# ==========================================================================

class RecallDetector:
    """Live recall@k vs the generation's scorecard baseline, per rung,
    over a fast (recent deque, ~minutes at shipped sampling) and a slow
    (generation-wide Algorithm-R reservoir, ~hours) window.

    A rung trips only when BOTH window means sit more than ``tolerance``
    below its baked baseline AND both windows carry ``min_samples`` —
    the fast window proves it's still happening, the slow one that the
    generation's whole serving stream regressed, not one burst; cold
    rungs pass through.  Hysteresis is asymmetric per rung (trip
    instantly, clear after a ``recovery_s`` dwell).  Thread-safe;
    ``clock``/``rng`` injectable — tests drive hours in microseconds."""

    MIN_TICK_INTERVAL_S = 1.0

    def __init__(self, config: RecallConfig,
                 scorecard: Optional[RecallScorecard] = None, *,
                 reporting_reason: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.config = config
        self.scorecard = scorecard
        self.reporting_reason = (
            reporting_reason if scorecard is None or reporting_reason
            else None)
        self._clock = clock
        self._rng = rng or random.Random(0)
        self._lock = threading.Lock()
        self._rungs: Dict[str, Dict[str, Any]] = {}
        self._last_tick: Optional[float] = None
        self._last: Dict[str, Any] = {}

    def _state(self, rung: str) -> Dict[str, Any]:
        st = self._rungs.get(rung)
        if st is None:
            st = {"fast": deque(), "fast_sum": 0.0,
                  "res": [], "res_sum": 0.0, "seen": 0,
                  "tripped": False, "clear_since": None}
            self._rungs[rung] = st
        return st

    def add(self, rung: str, recall: float) -> None:
        cfg = self.config
        r = float(recall)
        with self._lock:
            st = self._state(rung)
            st["seen"] += 1
            st["fast"].append(r)
            st["fast_sum"] += r
            if len(st["fast"]) > max(cfg.fast_window, 1):
                st["fast_sum"] -= st["fast"].popleft()
            if len(st["res"]) < max(cfg.reservoir, 1):
                st["res"].append(r)
                st["res_sum"] += r
            else:
                j = self._rng.randrange(st["seen"])
                if j < len(st["res"]):
                    st["res_sum"] += r - st["res"][j]
                    st["res"][j] = r

    def tick(self, force: bool = False) -> Dict[str, Any]:
        """Recompute per-rung means + the hysteresis verdict
        (pull-driven with tick coalescing, like the drift detector)."""
        cfg = self.config
        now = self._clock()
        with self._lock:
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self.MIN_TICK_INTERVAL_S
                    and self._last):
                return dict(self._last)
            self._last_tick = now
            rungs: Dict[str, Any] = {}
            any_tripped = False
            any_enough = False
            for rung in sorted(self._rungs):
                st = self._rungs[rung]
                n_fast, n_slow = len(st["fast"]), len(st["res"])
                fast = st["fast_sum"] / n_fast if n_fast else None
                slow = st["res_sum"] / n_slow if n_slow else None
                baseline = (self.scorecard.expected(rung, cfg.k)
                            if self.scorecard is not None else None)
                enough = (n_fast >= cfg.min_samples
                          and n_slow >= cfg.min_samples)
                # Trip needs BOTH windows below baseline − tolerance.
                trip = (baseline is not None and enough
                        and baseline - fast > cfg.tolerance
                        and baseline - slow > cfg.tolerance)
                if trip:
                    st["tripped"] = True
                    st["clear_since"] = None
                elif st["tripped"]:
                    if st["clear_since"] is None:
                        st["clear_since"] = now
                    elif now - st["clear_since"] >= cfg.recovery_s:
                        st["tripped"] = False
                        st["clear_since"] = None
                any_tripped = any_tripped or st["tripped"]
                any_enough = any_enough or enough
                rungs[rung] = {
                    "recallFast": (round(fast, 4)
                                   if fast is not None else None),
                    "recallSlow": (round(slow, 4)
                                   if slow is not None else None),
                    "baseline": (round(baseline, 4)
                                 if baseline is not None else None),
                    "nFast": n_fast,
                    "nSlow": n_slow,
                    "tripped": st["tripped"],
                }
            state = {
                "reportingOnly": bool(self.reporting_reason),
                "reason": self.reporting_reason,
                "tripped": any_tripped,
                "insufficient": not any_enough,
                "rungs": rungs,
                "k": cfg.k,
                "tolerance": cfg.tolerance,
                "minSamples": cfg.min_samples,
            }
            self._last = state
            return dict(state)


# ==========================================================================
# The monitor: capture hook + off-thread exact re-rank + gate verdict
# ==========================================================================

class RecallMonitor:
    """The engine server's recall layer: one instance per server.

    ``on_generation`` arms the facade hook on the new generation's
    retriever(s) and re-anchors the detector on the wrapper's baked
    :class:`RecallScorecard`; ``_capture`` is the retrieval-facade hot
    path (two comparisons + one bounded enqueue on sampled
    approximate-rung requests); the worker thread re-scores captures
    exactly; ``augment_quality`` folds the verdict into the
    ``/quality.json`` gate.  With ``PIO_RECALL=off`` every method is an
    inert no-op, the hook is never attached, and no instruments
    register."""

    def __init__(self, config: Optional[RecallConfig] = None, *,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.config = config or RecallConfig.from_env()
        self.enabled = self.config.enabled
        self._clock = clock
        self._rng = rng or random.Random()
        if not self.enabled:
            return
        reg = registry or get_registry()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._generation = 0
        self._detector = RecallDetector(self.config, None, clock=clock)
        # retriever (weak) -> generation it serves; + the retrievers the
        # current generation armed, so a swap can detach the old hooks.
        self._gen_of: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._item_cells: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._armed: List[Any] = []   # weakrefs of hooked retrievers
        # cumulative per-rung miss attribution for the saturation gauges
        self._miss: Dict[str, Dict[str, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._g_recall = reg.gauge(
            "pio_retrieval_recall",
            "Live sampled recall@k of the approximate retrieval rungs "
            "vs an exact re-rank of the same generation's corpus.",
            ("rung", "k", "window"))
        self._g_baseline = reg.gauge(
            "pio_retrieval_recall_baseline",
            "Train-time expected recall@k baked into the generation's "
            "RecallScorecard.", ("rung", "k"))
        self._m_captures = reg.counter(
            "pio_retrieval_recall_captures_total",
            "Sampled retrieval captures by outcome (captured / scored / "
            "dropped / stale / dead / error).", ("result",))
        self._g_scanned = reg.gauge(
            "pio_retrieval_recall_scanned_fraction",
            "Mean fraction of corpus rows the approximate rung actually "
            "scanned for the sampled requests.", ("rung",))
        self._g_shortlist = reg.gauge(
            "pio_retrieval_recall_shortlist_saturation",
            "Share of missed true-top-k items whose cell WAS probed — "
            "the PQ rerank shortlist saturated; raise PIO_PQ_RERANK.",
            ("rung",))
        self._g_cell = reg.gauge(
            "pio_retrieval_recall_cell_miss",
            "Share of missed true-top-k items whose cell was NOT probed "
            "— the probe ring is too narrow; widen PIO_IVF_NPROBE.",
            ("rung",))
        self._g_tripped = reg.gauge(
            "pio_retrieval_recall_tripped",
            "1 while sampled recall sits below the generation's own "
            "baseline on both windows (hysteresis-latched).")
        self._g_reporting = reg.gauge(
            "pio_retrieval_recall_reporting_only",
            "1 while the recall monitor runs without a trusted "
            "scorecard (missing or fingerprint-mismatched) — reporting, "
            "never gating.")

    # -- sampling ------------------------------------------------------------

    def draw(self) -> float:
        """Per-request uniform draw, used only when the quality layer
        (the usual owner of the shared draw) is disabled."""
        return self._rng.random()

    # -- generation lifecycle ------------------------------------------------

    def on_generation(self, generation: int, models: Sequence[Any]
                      ) -> None:
        """Re-anchor on a swap (reload or rollback): detach the old
        generation's facade hooks, arm the new generation's
        retriever(s), and point the detector at the new wrapper's baked
        scorecard.  Idempotent and cheap — called right after
        ``QualityMonitor.on_generation``."""
        if not self.enabled:
            return
        scorecard, reason = resolve_recall_scorecard(models)
        if scorecard is None:
            logger.info(
                "recall: generation %d has no usable recall scorecard "
                "(%s) — recall monitoring is reporting-only",
                generation, reason)
        with self._lock:
            for ref in self._armed:
                r = ref()
                if r is not None:
                    r.recall_hook = None
            self._armed = []
            self._generation = generation
            self._detector = RecallDetector(
                self.config, scorecard, reporting_reason=reason,
                clock=self._clock)
            self._miss = {}
            self._queue.clear()
        # Arm OUTSIDE the monitor lock, and WITHOUT forcing retriever
        # creation: `arm_on_create` fires the callback immediately for
        # an already-cached retriever, else right after the facade
        # lazily builds it on the first query — retriever construction
        # (and its index fingerprint validation) keeps its load-is-lazy
        # contract.
        from predictionio_tpu.retrieval import arm_on_create

        for m in models or ():
            if not callable(getattr(m, "retriever", None)):
                continue
            try:
                arm_on_create(
                    m, lambda r, g=generation: self._arm(r, g))
            except Exception:
                logger.debug("recall: arm_on_create failed",
                             exc_info=True)
        self._g_reporting.set(1 if scorecard is None else 0)

    def _arm(self, retriever, generation: int) -> None:
        """Attach the capture hook to one retriever — possibly later
        than ``on_generation`` (first query builds the retriever).  A
        callback that fires after a further swap is stale and no-ops."""
        if retriever is None or not hasattr(retriever, "recall_hook"):
            return
        with self._lock:
            if self._closed or generation != self._generation:
                return
            retriever.recall_hook = self._capture
            self._gen_of[retriever] = generation
            self._armed.append(weakref.ref(retriever))

    # -- the facade hot-path hook --------------------------------------------

    def _capture(self, retriever, plan, queries, ids, scanned: int
                 ) -> None:
        """Called by ``Retriever.topk`` after an approximate-rung
        answer.  Cost when unsampled: one contextvar read + one compare.
        Sampled: bounded copies of the first ``max_rows`` query/id rows
        into the queue (drop-and-count on overflow — never blocks the
        dispatch)."""
        u = active_sample_u()
        if u is None or u >= self.config.sample:
            return
        rows = min(len(queries), self.config.max_rows)
        rec = {
            "retriever": weakref.ref(retriever),
            "generation": self._gen_of.get(retriever),
            "rung": plan.rung,
            "nprobe": plan.nprobe,
            "rerank": plan.rerank,
            "q": queries[:rows].copy(),
            "ids": ids[:rows].copy(),
            "scanned": int(scanned),
            "batch": len(queries),
        }
        with self._cond:
            if self._closed:
                return
            if len(self._queue) >= max(self.config.queue, 1):
                self._m_captures.inc(result="dropped")
                return
            self._queue.append(rec)
            self._m_captures.inc(result="captured")
            # Wake the worker eagerly only under backpressure (queue
            # half full): a per-capture notify turns every sampled
            # request into a thread wakeup + GIL handoff on the serving
            # hot path — measurable p99 inflation at saturation.  The
            # steady state rides the worker's short poll instead and
            # drains captures in batches.
            if len(self._queue) * 2 >= max(self.config.queue, 1):
                self._cond.notify()
        self._ensure_thread()

    # -- the worker ----------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="pio-recall-monitor", daemon=True)
            self._thread.start()

    #: Worker poll period: captures queue for at most this long before a
    #: batch drain when the backpressure notify hasn't fired.  Recall is
    #: a minutes-scale signal — a quarter second of added measurement
    #: latency buys per-request wakeups off the serving path.
    DRAIN_INTERVAL_S = 0.25

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._queue and not self._closed:
                    self._cond.wait(timeout=self.DRAIN_INTERVAL_S)
                if self._closed:
                    return
            try:
                while self.drain_once():
                    pass
            except Exception:
                logger.exception("recall monitor worker error")

    def drain_once(self) -> int:
        """Exact-re-rank one queued capture (also the tests' synchronous
        entry point).  Returns captures processed (0/1)."""
        with self._lock:
            if not self._queue:
                return 0
            rec = self._queue.popleft()
            current_gen = self._generation
        r = rec["retriever"]()
        if r is None:
            self._m_captures.inc(result="dead")
            return 1
        if rec["generation"] != current_gen:
            self._m_captures.inc(result="stale")
            return 1
        try:
            self._score(r, rec)
        except Exception:
            logger.debug("recall re-score failed", exc_info=True)
            self._m_captures.inc(result="error")
            return 1
        self._m_captures.inc(result="scored")
        return 1

    def _cells_of(self, retriever, index):
        """item -> IVF cell lookup array, built once per retriever
        (weak-keyed — dies with the generation's staged corpus)."""
        cells = self._item_cells.get(retriever)
        if cells is None:
            import numpy as np

            cells = np.full(index.n_items, -1, dtype=np.int32)
            for c in range(index.nlist):
                ln = int(index.list_lengths[c])
                if ln:
                    cells[index.lists[c, :ln]] = c
            self._item_cells[retriever] = cells
        return cells

    def _score(self, retriever, rec: Dict[str, Any]) -> None:
        import numpy as np

        cfg = self.config
        rung = rec["rung"]
        q, ids = rec["q"], rec["ids"]
        k = min(cfg.k, ids.shape[1], retriever.n_items)
        if k <= 0:
            return
        host = retriever.host_vecs()
        exact = _exact_topk_ids(host, q, k)
        shortlist_misses = cell_misses = 0
        truth_total = 0
        probe_sets: Optional[List[set]] = None
        cells = None
        if rung == "ivf_pq":
            index = retriever.ivf_index()
            if index is not None:
                cq = np.ascontiguousarray(q, dtype=np.float32) \
                    @ index.centroids.T
                nprobe = max(1, min(int(rec["nprobe"]) or index.nlist,
                                    index.nlist))
                if nprobe < index.nlist:
                    probed = np.argpartition(
                        -cq, nprobe - 1, axis=1)[:, :nprobe]
                else:
                    probed = np.broadcast_to(
                        np.arange(index.nlist), cq.shape)
                probe_sets = [set(int(c) for c in row) for row in probed]
                cells = self._cells_of(retriever, index)
        for b in range(len(q)):
            truth = [int(i) for i in exact[b] if i >= 0]
            got = {int(i) for i in ids[b, :k] if i >= 0}
            truth_total += len(truth)
            missed = [i for i in truth if i not in got]
            self._detector.add(
                rung, 1.0 if not truth
                else (len(truth) - len(missed)) / len(truth))
            for i in missed:
                if rung == "ivf":
                    # in-cell scan is exact: a miss IS an unprobed cell
                    cell_misses += 1
                elif rung == "pq_flat":
                    # every code row scanned: a miss IS a saturated
                    # (or out-ordered) shortlist
                    shortlist_misses += 1
                elif probe_sets is not None and cells is not None:
                    if int(cells[i]) in probe_sets[b]:
                        shortlist_misses += 1
                    else:
                        cell_misses += 1
                else:
                    shortlist_misses += 1
        frac = rec["scanned"] / max(rec["batch"] * retriever.n_items, 1)
        with self._lock:
            agg = self._miss.setdefault(
                rung, {"truth": 0, "shortlist": 0, "cell": 0,
                       "scanned_sum": 0.0, "captures": 0})
            agg["truth"] += truth_total
            agg["shortlist"] += shortlist_misses
            agg["cell"] += cell_misses
            agg["scanned_sum"] += frac
            agg["captures"] += 1

    # -- verdict / views -----------------------------------------------------

    def payload(self) -> Dict[str, Any]:
        """The ``recall`` block of ``/quality.json`` (gauges published
        as a side effect, same pull-driven pattern as the quality
        payload)."""
        if not self.enabled:
            return {"enabled": False}
        state = self._detector.tick()
        with self._lock:
            miss = {rung: dict(agg) for rung, agg in self._miss.items()}
        rungs: Dict[str, Any] = {}
        for rung, det in (state.get("rungs") or {}).items():
            agg = miss.get(rung, {})
            truth = agg.get("truth", 0)
            caps = agg.get("captures", 0)
            row = dict(det)
            row["shortlistSaturation"] = (
                round(agg.get("shortlist", 0) / truth, 4) if truth
                else None)
            row["cellMiss"] = (
                round(agg.get("cell", 0) / truth, 4) if truth else None)
            row["scannedFraction"] = (
                round(agg.get("scanned_sum", 0.0) / caps, 6) if caps
                else None)
            rungs[rung] = row
        tripped = bool(state.get("tripped"))
        reporting = bool(state.get("reportingOnly"))
        if reporting:
            verdict = "reporting_only"
        elif tripped:
            verdict = "degraded"
        elif state.get("insufficient", True):
            verdict = "insufficient"
        else:
            verdict = "healthy"
        k_label = str(self.config.k)
        for rung, row in rungs.items():
            for window, key in (("fast", "recallFast"),
                                ("slow", "recallSlow")):
                v = row.get(key)
                if v is not None:
                    self._g_recall.set(v, rung=rung, k=k_label,
                                       window=window)
            if row.get("baseline") is not None:
                self._g_baseline.set(row["baseline"], rung=rung,
                                     k=k_label)
            for gauge, key in ((self._g_shortlist,
                                "shortlistSaturation"),
                               (self._g_cell, "cellMiss"),
                               (self._g_scanned, "scannedFraction")):
                if row.get(key) is not None:
                    gauge.set(row[key], rung=rung)
        self._g_tripped.set(1 if tripped else 0)
        self._g_reporting.set(1 if reporting else 0)
        return {
            "enabled": True,
            "generation": self._generation,
            "verdict": verdict,
            "tripped": tripped,
            "reportingOnly": reporting,
            "reason": state.get("reason"),
            "insufficient": bool(state.get("insufficient", True)),
            "sample": self.config.sample,
            "k": self.config.k,
            "tolerance": self.config.tolerance,
            "minSamples": self.config.min_samples,
            "captured": int(self._m_captures.value(result="captured")),
            "scored": int(self._m_captures.value(result="scored")),
            "dropped": int(self._m_captures.value(result="dropped")),
            "rungs": rungs,
            "scorecard": (
                self._detector.scorecard.summary()
                if self._detector.scorecard is not None
                else {"present": False,
                      "reason": self._detector.reporting_reason}),
        }

    def augment_quality(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Fold the recall verdict into a ``/quality.json`` document as
        the gate's third reason.

        With ``PIO_RECALL=off`` the document passes through UNTOUCHED
        (the kill switch can never block a promotion).  With the quality
        layer itself off but recall on, a minimal gate-bearing document
        is synthesized so the refresh daemon's canary watch and the
        fleet rollout bake (both read only ``gate.rollback``) stay
        live."""
        if not self.enabled:
            return doc
        recall = self.payload()
        gates = (recall["tripped"] and not recall["reportingOnly"]
                 and self.config.gate)
        if not isinstance(doc, dict) or not doc.get("enabled"):
            return {
                "enabled": True,
                "qualityLayerEnabled": False,
                "generation": recall["generation"],
                "verdict": recall["verdict"],
                "gate": {"enabled": self.config.gate,
                         "rollback": gates,
                         "reasons": (["recall_regression"] if gates
                                     else [])},
                "recall": recall,
            }
        out = dict(doc)
        out["recall"] = recall
        gate = dict(out.get("gate") or {})
        reasons = list(gate.get("reasons") or ())
        if gates:
            if "recall_regression" not in reasons:
                reasons.append("recall_regression")
            gate["rollback"] = True
            out["verdict"] = "degraded"
        gate["reasons"] = reasons
        out["gate"] = gate
        return out

    def close(self) -> None:
        if not self.enabled:
            return
        with self._cond:
            self._closed = True
            self._queue.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
