"""Model-quality observability (ISSUE 11 tentpole).

PRs 1/3/9 gave the server eyes for *how fast* it serves; this module
gives it eyes for *what* it serves.  Since PR 10 the system continuously
retrains and promotes generations behind a canary gate that checks NaN,
golden queries, and latency/availability SLO burn — but never prediction
quality: a warm-start that quietly collapses score diversity or drifts
the score distribution sails through every existing gate.  Four parts,
one `/quality.json` document:

- **Prediction record stream** — a per-request sampling decision
  (``PIO_QUALITY_SAMPLE``, ONE RNG draw shared with the
  ``PIO_REQUEST_LOG`` wide-event sampler) feeds a per-generation score
  reservoir + a recent-window deque at the scheduler's dispatch
  boundary.  Exported: ``pio_predict_score`` (served score
  distribution), candidate-diversity / top-item-concentration gauges,
  empty-result and fold-in-share readings.
- **Drift detection** — PSI/KL between the served score distribution
  and a training-time baseline :class:`Scorecard` serialized INSIDE the
  model wrapper (riding the PR-8 versioned-with-generation +
  fingerprint pattern): the staged-reload/rollback swap moves scorecard
  and model atomically, and a mismatched/missing scorecard degrades
  LOUDLY to reporting-only — it never blocks serving.  Tripping needs
  the PSI over threshold on BOTH the fast (recent deque) and slow
  (generation reservoir) windows; hysteresis is asymmetric exactly like
  the SLO engine's (trip instantly, clear after a
  ``PIO_QUALITY_RECOVERY_S`` trip-false dwell).
- **Shadow-scored canary divergence** — during the canary window the
  RETAINED previous generation re-scores a sampled slice of live
  queries off-thread (bounded queue, drop-on-full: shadow work may
  never add serving latency), and rank-overlap@k / relative
  score-delta percentiles between old and new become a promotion gate
  the refresh daemon's ``HttpPromoter`` acts on exactly as it does on
  SLO burn.
- **Feedback join** — sampled responses carry an ``X-PIO-Serve-Id``
  whose events-echo (``properties.pioServeId`` on a subsequent
  buy/rate) the event server joins back to the served item set within a
  TTL window → online hit-rate per generation.

Cold-app pass-through is a hard rule: with fewer than
``PIO_QUALITY_MIN_SAMPLES`` sampled predictions (or shadow pairs) the
verdict is ``insufficient`` and the gate NEVER fires — a cold app must
pass through, not be blocked by its own silence.

Env knobs (all read by :meth:`QualityConfig.from_env`):

====================================  ==================================
``PIO_QUALITY``                       master kill switch (default on;
                                      off disables every hook)
``PIO_QUALITY_SAMPLE``                per-request prediction-stream
                                      sampling rate (default 0.1)
``PIO_QUALITY_RESERVOIR``             generation score reservoir = the
                                      slow drift window (4096)
``PIO_QUALITY_FAST_WINDOW``           recent-sample deque = the fast
                                      drift window (512)
``PIO_QUALITY_MIN_SAMPLES``           cold-app pass-through floor (100)
``PIO_QUALITY_PSI_THRESHOLD``         PSI trip point, both windows
                                      (0.25 — the classic "significant
                                      shift" convention)
``PIO_QUALITY_RECOVERY_S``            trip-false dwell before the drift
                                      verdict clears (60)
``PIO_QUALITY_GATE``                  quality verdicts may roll back a
                                      promotion (default on; off =
                                      report-only)
``PIO_SHADOW_SAMPLE``                 shadow-scored slice of live
                                      queries in the canary window
                                      (0.25)
``PIO_SHADOW_MIN_OVERLAP``            mean rank-overlap@k below this =
                                      divergent (0.5)
``PIO_SHADOW_QUEUE``                  bounded shadow queue; overflow
                                      drops, never blocks (256)
``PIO_QUALITY_FEEDBACK_TTL_S``        serve→feedback join window (1800)
``PIO_QUALITY_FEEDBACK_EVENTS``       event names that count as
                                      feedback (csv; "buy,rate")
====================================  ==================================

stdlib-only on import (the event server and CLI ride it jax/numpy-free);
:func:`scorecard_from_matrix` imports numpy lazily at train time.
"""

from __future__ import annotations

import bisect
import dataclasses
import logging
import math
import os
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.config import env_bool
from predictionio_tpu.obs.metrics import get_registry

logger = logging.getLogger(__name__)

__all__ = [
    "QualityConfig",
    "Scorecard",
    "scorecard_from_scores",
    "scorecard_from_matrix",
    "psi",
    "kl_divergence",
    "DriftDetector",
    "ShadowScorer",
    "FeedbackJoiner",
    "QualityMonitor",
    "extract_result_items",
    "resolve_scorecard",
    "merge_quality",
    "feedback_joiner",
    "note_feedback_events",
    "generation_of_serve_id",
    "reset_quality",
    "SERVE_ID_HEADER",
    "SERVE_ID_PROPERTY",
]

SERVE_ID_HEADER = "X-PIO-Serve-Id"
SERVE_ID_PROPERTY = "pioServeId"

# Served-score distribution buckets: affinity/similarity scores from the
# shipped engines live in single digits (normalized tower dot products,
# ALS rating reconstructions); wide tails catch mis-scaled generations.
SCORE_BUCKETS = (-100.0, -10.0, -5.0, -2.0, -1.0, -0.5, -0.2, 0.0,
                 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 100.0)
# Relative score-delta buckets for shadow scoring (|new-old| / |old|).
SHADOW_DELTA_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                        0.1, 0.25, 0.5, 1.0, 2.5)

_EPS = 1e-6


def _env_f(env, key: str, default: float) -> float:
    raw = env.get(key)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class QualityConfig:
    """Quality-layer knobs; :meth:`from_env` is the production
    constructor (same pattern as SchedulerConfig/SLOConfig)."""

    enabled: bool = True
    sample: float = 0.1
    reservoir: int = 4096
    fast_window: int = 512
    min_samples: int = 100
    psi_threshold: float = 0.25
    recovery_s: float = 60.0
    gate: bool = True
    shadow_sample: float = 0.25
    shadow_min_overlap: float = 0.5
    shadow_queue: int = 256
    feedback_ttl_s: float = 1800.0
    feedback_events: Tuple[str, ...] = ("buy", "rate")

    @classmethod
    def from_env(cls, env=None) -> "QualityConfig":
        env = os.environ if env is None else env
        raw_events = env.get("PIO_QUALITY_FEEDBACK_EVENTS", "")
        events = tuple(e.strip() for e in raw_events.split(",")
                       if e.strip()) or ("buy", "rate")
        return cls(
            enabled=env_bool(env.get("PIO_QUALITY"), True),
            sample=min(max(_env_f(env, "PIO_QUALITY_SAMPLE", 0.1), 0.0),
                       1.0),
            reservoir=int(_env_f(env, "PIO_QUALITY_RESERVOIR", 4096)),
            fast_window=int(_env_f(env, "PIO_QUALITY_FAST_WINDOW", 512)),
            min_samples=int(_env_f(env, "PIO_QUALITY_MIN_SAMPLES", 100)),
            psi_threshold=_env_f(env, "PIO_QUALITY_PSI_THRESHOLD", 0.25),
            recovery_s=_env_f(env, "PIO_QUALITY_RECOVERY_S", 60.0),
            gate=env_bool(env.get("PIO_QUALITY_GATE"), True),
            shadow_sample=min(max(
                _env_f(env, "PIO_SHADOW_SAMPLE", 0.25), 0.0), 1.0),
            shadow_min_overlap=_env_f(env, "PIO_SHADOW_MIN_OVERLAP", 0.5),
            shadow_queue=int(_env_f(env, "PIO_SHADOW_QUEUE", 256)),
            feedback_ttl_s=_env_f(env, "PIO_QUALITY_FEEDBACK_TTL_S",
                                  1800.0),
            feedback_events=events,
        )


# ==========================================================================
# Scorecard: the training-time baseline that rides the model wrapper
# ==========================================================================

@dataclasses.dataclass
class Scorecard:
    """Training-time score-distribution baseline.

    Serialized INSIDE the model wrapper (next to the PR-8 IVF index), so
    the staged-reload/rollback generation swap moves scorecard and model
    as ONE artifact — serving can never diff generation-N scores against
    a generation-M baseline.  ``fingerprint`` is the PR-8 corpus
    fingerprint of the vectors the baseline was scored over; a wrapper
    whose corpus no longer matches degrades the drift detector to
    reporting-only (loud, never blocking).
    """

    edges: Tuple[float, ...]     # interior bin edges (B bins = B-1 edges)
    probs: Tuple[float, ...]     # baseline probability mass per bin
    n: int                       # baseline sample size
    mean: float
    std: float
    fingerprint: Optional[str] = None
    built_at: float = 0.0
    name: str = ""

    def bin_index(self, value: float) -> int:
        return bisect.bisect_right(self.edges, value)

    def summary(self) -> Dict[str, Any]:
        return {"present": True, "bins": len(self.probs), "n": self.n,
                "mean": round(self.mean, 4), "std": round(self.std, 4),
                "builtAt": round(self.built_at, 3), "name": self.name,
                "fingerprint": self.fingerprint}


def scorecard_from_scores(scores: Sequence[float], *, bins: int = 16,
                          fingerprint: Optional[str] = None,
                          name: str = "") -> Optional[Scorecard]:
    """Build a baseline from a flat score sample (quantile bin edges, so
    every baseline bin carries mass and PSI is well-conditioned).
    Returns None when the sample is degenerate (<2 distinct values) —
    callers ship no scorecard rather than a meaningless one."""
    vals = sorted(float(s) for s in scores
                  if s == s and math.isfinite(float(s)))
    if len(vals) < 2 or vals[0] == vals[-1]:
        return None
    edges: List[float] = []
    for i in range(1, max(bins, 2)):
        pos = min(int(len(vals) * i / bins), len(vals) - 1)
        v = vals[pos]
        # Edge at the MIDPOINT to the next distinct value, never on an
        # observed score: serving recomputes the same scores through a
        # different op order (retriever rungs, device matmuls), and a
        # baseline value sitting exactly on its own edge would flip bins
        # on a 1-ulp difference — fake drift on a healthy server.
        nxt = next((w for w in vals[pos:] if w > v), None)
        if nxt is None:
            continue
        e = (v + nxt) / 2.0
        if not edges or e > edges[-1]:
            edges.append(e)
    counts = [0] * (len(edges) + 1)
    for v in vals:
        counts[bisect.bisect_right(edges, v)] += 1
    n = len(vals)
    mean = sum(vals) / n
    var = sum((v - mean) ** 2 for v in vals) / n
    return Scorecard(edges=tuple(edges),
                     probs=tuple(c / n for c in counts),
                     n=n, mean=mean, std=math.sqrt(var),
                     fingerprint=fingerprint,
                     built_at=time.time(), name=name)


def scorecard_from_matrix(query_vecs, item_vecs, *, sample: int = 256,
                          seed: int = 0, bins: int = 16,
                          name: str = "") -> Optional[Scorecard]:
    """Train-time helper: the baseline is the RANK-1 (top) score of a
    seeded sample of query rows against the item corpus.

    Rank-1 — not top-K — because it is the one population invariant to
    the client's ``num``: serving results carry however many scores the
    query asked for, and a top-3 request's score set sits structurally
    above a top-10 one's, which would read as drift on a perfectly
    healthy server.  The serving detector feeds the same statistic (the
    max served score per sampled request).  Numpy imported lazily: this
    only runs inside ``pio train``."""
    import numpy as np

    q = np.asarray(query_vecs)
    it = np.asarray(item_vecs)
    if q.ndim != 2 or it.ndim != 2 or not len(q) or not len(it):
        return None
    rng = np.random.default_rng(seed)
    n_sample = min(len(q), max(int(sample), 1))
    idx = rng.choice(len(q), size=n_sample, replace=False)
    qs = q[idx]
    # Running max over item chunks: a single [sample, N] matmul is a
    # ~GB-scale transient at the million-item corpora the retrieval
    # layer targets — chunking keeps the peak at a few MB, identical
    # output.
    chunk = 65536
    top1 = np.full(n_sample, -np.inf, dtype=np.float64)
    for start in range(0, it.shape[0], chunk):
        block = qs @ it[start:start + chunk].T
        np.maximum(top1, block.max(axis=1), out=top1)
    from predictionio_tpu.retrieval.ivf import corpus_fingerprint

    return scorecard_from_scores(
        top1.tolist(), bins=bins,
        fingerprint=corpus_fingerprint(np.ascontiguousarray(it)),
        name=name)


def psi(expected: Sequence[float], actual: Sequence[float],
        eps: float = _EPS) -> float:
    """Population stability index over matched bins:
    ``Σ (a−e)·ln(a/e)``, with epsilon smoothing so an empty bin on
    either side stays finite.  Symmetric in direction of shift; ~0.1 =
    moderate, ≥0.25 = significant (the conventional trip point).

    ``eps`` matters: with a tiny fixed epsilon, one EMPTY bin in a
    small sample contributes ``(1/B)·ln(1/(B·eps))`` ≈ 0.7 of pure
    noise.  The drift detector passes a count-based floor (≈ half a
    sample's mass, ``0.5/n``) so small windows read sampling noise, not
    phantom drift."""
    out = 0.0
    for e, a in zip(expected, actual):
        e = max(float(e), eps)
        a = max(float(a), eps)
        out += (a - e) * math.log(a / e)
    return out


def kl_divergence(expected: Sequence[float], actual: Sequence[float],
                  eps: float = _EPS) -> float:
    """KL(actual ‖ expected) over matched bins, epsilon-smoothed (same
    count-based ``eps`` discipline as :func:`psi`)."""
    out = 0.0
    for e, a in zip(expected, actual):
        e = max(float(e), eps)
        a = max(float(a), eps)
        out += a * math.log(a / e)
    return out


# ==========================================================================
# Drift detection
# ==========================================================================

class DriftDetector:
    """PSI/KL of the served score distribution vs the generation's
    scorecard, over a fast (recent deque) and a slow (generation
    reservoir) window, with SLO-style asymmetric hysteresis.

    Scores are binned ONCE on ingest (``add`` stores bin indices and
    maintains both windows' counts incrementally — O(1) per sample,
    O(bins) per tick).  The reservoir is Algorithm-R: an unbiased
    generation-wide sample in bounded memory.  All methods are
    thread-safe; ``clock`` is injectable (tests drive hours of dwell in
    microseconds, zero wall sleeps)."""

    MIN_TICK_INTERVAL_S = 1.0

    def __init__(self, config: QualityConfig,
                 baseline: Optional[Scorecard] = None, *,
                 reporting_reason: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.config = config
        self.baseline = baseline
        self.reporting_reason = (
            reporting_reason if baseline is None or reporting_reason
            else None)
        self._clock = clock
        self._rng = rng or random.Random(0)
        self._lock = threading.Lock()
        n_bins = len(baseline.probs) if baseline else 0
        self._fast: deque = deque()          # bin indices, newest right
        self._fast_counts = [0] * n_bins
        self._res: List[int] = []            # reservoir of bin indices
        self._res_counts = [0] * n_bins
        self._seen = 0                       # total samples offered
        self._tripped = False
        self._tripped_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._last: Dict[str, Any] = {}

    def add(self, score: float) -> None:
        if self.baseline is None:
            with self._lock:
                self._seen += 1
            return
        b = self.baseline.bin_index(score)
        cfg = self.config
        with self._lock:
            self._seen += 1
            self._fast.append(b)
            self._fast_counts[b] += 1
            if len(self._fast) > max(cfg.fast_window, 1):
                self._fast_counts[self._fast.popleft()] -= 1
            if len(self._res) < max(cfg.reservoir, 1):
                self._res.append(b)
                self._res_counts[b] += 1
            else:
                j = self._rng.randrange(self._seen)
                if j < len(self._res):
                    self._res_counts[self._res[j]] -= 1
                    self._res[j] = b
                    self._res_counts[b] += 1

    @staticmethod
    def _probs(counts: List[int]) -> Tuple[List[float], int]:
        n = sum(counts)
        if n == 0:
            return [0.0] * len(counts), 0
        return [c / n for c in counts], n

    def tick(self, force: bool = False) -> Dict[str, Any]:
        """Recompute drift + the hysteresis verdict (pull-driven, tick
        coalescing like the SLO engine — a 1 Hz /quality.json poll costs
        one real recompute per second)."""
        now = self._clock()
        with self._lock:
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self.MIN_TICK_INTERVAL_S
                    and self._last):
                return dict(self._last)
            self._last_tick = now
            seen = self._seen
            if self.baseline is None:
                state = {"reportingOnly": True,
                         "reason": self.reporting_reason or "no_scorecard",
                         "tripped": False, "samples": seen,
                         "psi": {"fast": None, "slow": None},
                         "kl": {"fast": None, "slow": None},
                         "nFast": 0, "nSlow": 0,
                         "threshold": self.config.psi_threshold,
                         "minSamples": self.config.min_samples}
                self._last = state
                return dict(state)
            base = self.baseline.probs
            fast_p, n_fast = self._probs(self._fast_counts)
            slow_p, n_slow = self._probs(self._res_counts)
            # Count-based smoothing floor (≈ half a sample's mass): a
            # bin a small window happens not to have hit yet must read
            # as sampling noise, not as ~0.7 PSI of phantom drift.
            ef = max(_EPS, 0.5 / n_fast) if n_fast else _EPS
            es = max(_EPS, 0.5 / n_slow) if n_slow else _EPS
            psi_fast = psi(base, fast_p, eps=ef) if n_fast else 0.0
            psi_slow = psi(base, slow_p, eps=es) if n_slow else 0.0
            kl_fast = kl_divergence(base, fast_p, eps=ef) if n_fast \
                else 0.0
            kl_slow = kl_divergence(base, slow_p, eps=es) if n_slow \
                else 0.0
            thr = self.config.psi_threshold
            enough = (n_fast >= self.config.min_samples
                      and n_slow >= self.config.min_samples)
            # Trip needs BOTH windows over threshold (the fast window
            # proves it's still happening, the slow one that the whole
            # generation's serving stream shifted, not one burst).
            trip = enough and psi_fast >= thr and psi_slow >= thr
            if trip:
                if not self._tripped:
                    self._tripped = True
                    self._tripped_since = now
                self._clear_since = None
            elif self._tripped:
                if self._clear_since is None:
                    self._clear_since = now
                elif now - self._clear_since >= self.config.recovery_s:
                    self._tripped = False
                    self._tripped_since = None
                    self._clear_since = None
            state = {
                "reportingOnly": bool(self.reporting_reason),
                "reason": self.reporting_reason,
                "tripped": self._tripped,
                "trippedSinceS": (round(now - self._tripped_since, 1)
                                  if self._tripped_since is not None
                                  else None),
                "recoveringForS": (round(now - self._clear_since, 1)
                                   if self._clear_since is not None
                                   else None),
                "insufficient": not enough,
                "samples": seen,
                "psi": {"fast": round(psi_fast, 4),
                        "slow": round(psi_slow, 4)},
                "kl": {"fast": round(kl_fast, 4),
                       "slow": round(kl_slow, 4)},
                "nFast": n_fast, "nSlow": n_slow,
                "threshold": thr,
                "minSamples": self.config.min_samples,
            }
            self._last = state
            return dict(state)


# ==========================================================================
# Shadow-scored canary divergence
# ==========================================================================

class ShadowScorer:
    """Re-scores a sampled slice of live queries with the RETAINED
    previous generation during the canary window, off-thread.

    The serving hot path only ever enqueues (bounded deque; overflow
    drops and counts — shadow work must never add serving latency or
    block a dispatch).  The worker compares old vs new top-K:
    rank-overlap@k and relative score deltas on shared items.  A session
    is armed per promotion (:meth:`start`) and torn down on rollback /
    previous-generation eviction, dropping the strong reference to the
    old generation's closure so its memory can actually be freed."""

    def __init__(self, config: QualityConfig, registry=None):
        self.config = config
        reg = registry or get_registry()
        self._m_total = reg.counter(
            "pio_quality_shadow_total",
            "Shadow-scored canary pairs by outcome.", ("result",))
        self._m_overlap = reg.gauge(
            "pio_quality_shadow_overlap",
            "Mean rank-overlap@k between the serving generation and the "
            "shadow-scoring previous generation (1.0 = identical top-K).")
        self._m_delta = reg.histogram(
            "pio_quality_shadow_delta",
            "Relative score delta |new-old|/|old| on items both "
            "generations ranked.", (), buckets=SHADOW_DELTA_BUCKETS)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._fn: Optional[Callable[[Any], Any]] = None
        self._generation: Optional[int] = None
        self._prev_generation: Optional[int] = None
        self._overlaps: deque = deque(maxlen=512)
        self._scored = 0
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- session lifecycle --------------------------------------------------

    def start(self, generation: int, prev_generation: Optional[int],
              shadow_fn: Callable[[Any], Any]) -> None:
        """Arm a shadow session: ``shadow_fn(bound_query) -> result
        json`` runs the previous generation's predict stack."""
        with self._lock:
            self._fn = shadow_fn
            self._generation = generation
            self._prev_generation = prev_generation
            self._queue.clear()
            self._overlaps.clear()
            self._scored = 0
        self._ensure_thread()

    def stop(self, reason: str = "") -> None:
        """Disarm (rollback / eviction / shutdown): drops the previous
        generation's closure and the pending queue."""
        with self._lock:
            if self._fn is not None and reason:
                logger.info("shadow scoring stopped (%s)", reason)
            self._fn = None
            self._queue.clear()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._fn = None
            self._queue.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def active(self) -> bool:
        with self._lock:
            return self._fn is not None

    # -- the serving-side enqueue (hot path) --------------------------------

    def submit(self, query: Any, items: List[Tuple[Any, float]],
               generation: int) -> None:
        """Non-blocking: enqueue one (query, served top-K) pair for the
        worker; silently inert when no session is armed, drop-and-count
        when the bounded queue is full."""
        with self._cond:
            if self._fn is None or generation != self._generation:
                return
            if len(self._queue) >= max(self.config.shadow_queue, 1):
                self._m_total.inc(result="dropped")
                return
            self._queue.append((query, items))
            self._cond.notify()

    # -- the worker ---------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._loop, name="pio-shadow-scorer", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(timeout=1.0)
                if self._closed:
                    return
            try:
                self.drain_once()
            except Exception:
                logger.exception("shadow scorer worker error")

    def drain_once(self) -> int:
        """Score one queued pair (also the tests' synchronous entry
        point).  Returns the number of pairs processed (0/1)."""
        with self._lock:
            if not self._queue or self._fn is None:
                return 0
            query, new_items = self._queue.popleft()
            fn = self._fn
        try:
            old_result = fn(query)
        except Exception:
            logger.debug("shadow predict failed", exc_info=True)
            self._m_total.inc(result="error")
            return 1
        old_items = extract_result_items(old_result) or []
        self._observe_pair(new_items, old_items)
        return 1

    def _observe_pair(self, new_items: List[Tuple[Any, float]],
                      old_items: List[Tuple[Any, float]]) -> None:
        k = min(len(new_items), len(old_items))
        if k == 0:
            # Both empty = the generations agree; one-sided empty is
            # total divergence for this query.
            overlap = 1.0 if len(new_items) == len(old_items) else 0.0
        else:
            new_ids = [i for i, _ in new_items[:k]]
            old_map = {i: s for i, s in old_items}
            shared = [i for i in new_ids if i in old_map]
            overlap = len(shared) / k
            new_map = {i: s for i, s in new_items}
            for i in shared:
                denom = abs(old_map[i]) + _EPS
                self._m_delta.observe(abs(new_map[i] - old_map[i]) / denom)
        with self._lock:
            self._overlaps.append(overlap)
            self._scored += 1
            mean = sum(self._overlaps) / len(self._overlaps)
        self._m_total.inc(result="scored")
        self._m_overlap.set(mean)

    # -- verdict ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            overlaps = sorted(self._overlaps)
            scored = self._scored
            active = self._fn is not None
            gen, prev = self._generation, self._prev_generation

        def _pct(p):
            if not overlaps:
                return None
            return round(
                overlaps[min(int(p * len(overlaps)), len(overlaps) - 1)], 4)

        n = len(overlaps)
        mean = round(sum(overlaps) / n, 4) if n else None
        enough = scored >= self.config.min_samples
        divergent = (active and enough and mean is not None
                     and mean < self.config.shadow_min_overlap)
        return {
            "active": active,
            "generation": gen,
            "previousGeneration": prev,
            "scored": scored,
            "insufficient": not enough,
            "overlapMean": mean,
            "overlapP10": _pct(0.10),
            "overlapP50": _pct(0.50),
            "minOverlap": self.config.shadow_min_overlap,
            "divergent": divergent,
        }


# ==========================================================================
# Feedback join (event server side)
# ==========================================================================

def generation_of_serve_id(serve_id: str) -> Optional[int]:
    """Serve ids are ``g<generation>-<nonce>`` so a conversion can be
    attributed to a generation even when the serve record is gone
    (expired TTL or a different serving process)."""
    if not serve_id.startswith("g"):
        return None
    head = serve_id[1:].split("-", 1)[0]
    try:
        return int(head)
    except ValueError:
        return None


class FeedbackJoiner:
    """Joins served recommendations to subsequent feedback events.

    The engine server registers each sampled serve (:meth:`note_serve`:
    serve id → generation + served item set, TTL-bounded); the event
    server hands every landed feedback event that echoes a serve id to
    :meth:`feedback`.  A hit = the event's target item was in the served
    set within the TTL window → online hit-rate per generation.  All
    state is process-local and bounded: a cross-process deployment still
    counts per-generation attributed conversions (``unmatched``) via the
    id prefix, but item-level hit/miss needs the serve record (README
    documents the caveat)."""

    def __init__(self, ttl_s: float = 1800.0, *, max_records: int = 20000,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.ttl_s = float(ttl_s)
        self.max_records = int(max_records)
        self._clock = clock
        self._lock = threading.Lock()
        self._records: "OrderedDict[str, Tuple[int, frozenset, float]]" = \
            OrderedDict()
        # generation -> [hits, misses, attributed-but-untracked]
        self._per_gen: Dict[int, List[int]] = {}
        reg = registry or get_registry()
        self._m_feedback = reg.counter(
            "pio_quality_feedback_total",
            "Feedback events joined to served recommendations by outcome "
            "(hit/miss/expired/unmatched).", ("result",))
        self._m_hit_rate = reg.gauge(
            "pio_quality_online_hit_rate",
            "Online hit-rate of the newest generation with joined "
            "feedback (hits / (hits+misses)).")

    def note_serve(self, serve_id: str, generation: int,
                   items: Sequence[Any]) -> None:
        now = self._clock()
        with self._lock:
            self._records[serve_id] = (int(generation),
                                       frozenset(items), now)
            self._records.move_to_end(serve_id)
            self._evict(now)

    def _evict(self, now: float) -> None:
        # oldest-first: insertion order is time order
        while self._records:
            sid, (_, _, t) = next(iter(self._records.items()))
            if now - t > self.ttl_s or len(self._records) > self.max_records:
                del self._records[sid]
            else:
                break
        while len(self._records) > self.max_records:
            self._records.popitem(last=False)

    def feedback(self, serve_id: str, item: Optional[Any],
                 event_name: str = "") -> str:
        """Join one feedback event; returns the outcome recorded."""
        now = self._clock()
        with self._lock:
            rec = self._records.get(serve_id)
            if rec is None:
                gen = generation_of_serve_id(serve_id)
                result = "unmatched"
                if gen is not None:
                    self._per_gen.setdefault(gen, [0, 0, 0])[2] += 1
            else:
                gen, items, t = rec
                if now - t > self.ttl_s:
                    del self._records[serve_id]
                    result = "expired"
                else:
                    row = self._per_gen.setdefault(gen, [0, 0, 0])
                    if item is not None and item in items:
                        row[0] += 1
                        result = "hit"
                    else:
                        row[1] += 1
                        result = "miss"
            newest = max(self._per_gen) if self._per_gen else None
            rate = None
            if newest is not None:
                h, m, _ = self._per_gen[newest]
                rate = h / (h + m) if (h + m) else None
        self._m_feedback.inc(result=result)
        if rate is not None:
            self._m_hit_rate.set(rate)
        return result

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            gens = {
                str(g): {"hits": row[0], "misses": row[1],
                         "attributedOnly": row[2],
                         "hitRate": (round(row[0] / (row[0] + row[1]), 4)
                                     if row[0] + row[1] else None)}
                for g, row in sorted(self._per_gen.items())}
            tracked = len(self._records)
        return {"ttlS": self.ttl_s, "tracked": tracked,
                "generations": gens}


# Process-global joiner: the engine server notes serves, the event
# server joins feedback — in a single-process deployment (tests, bench,
# `pio deploy` + eventserver threads) they meet here.
_joiner: Optional[FeedbackJoiner] = None
_joiner_lock = threading.Lock()


def feedback_joiner() -> FeedbackJoiner:
    global _joiner
    with _joiner_lock:
        if _joiner is None:
            cfg = QualityConfig.from_env()
            _joiner = FeedbackJoiner(ttl_s=cfg.feedback_ttl_s)
        return _joiner


def note_feedback_events(events) -> None:
    """Event-server ingest hook: join every LANDED event that echoes a
    serve id (``properties.pioServeId``) and whose name is a configured
    feedback event.  One env check when quality is off — the kill
    switch disables this hook like every other."""
    cfg = QualityConfig.from_env()
    if not cfg.enabled:
        return
    j = None
    for ev in events:
        name = getattr(ev, "event", None)
        if cfg.feedback_events and name not in cfg.feedback_events:
            continue
        props = getattr(ev, "properties", None)
        sid = props.get(SERVE_ID_PROPERTY) if props is not None else None
        if not sid:
            continue
        if j is None:
            j = feedback_joiner()
        j.feedback(str(sid), getattr(ev, "target_entity_id", None),
                   str(name))


def reset_quality() -> None:
    """Drop the process-global joiner (test isolation)."""
    global _joiner
    with _joiner_lock:
        _joiner = None


# ==========================================================================
# Result introspection + scorecard resolution
# ==========================================================================

def extract_result_items(result: Any) -> Optional[List[Tuple[Any, float]]]:
    """``[(item, score), ...]`` out of a served result JSON, or None for
    result shapes that carry no score distribution (quality stays inert
    for such engines).  Handles the recommendation-shaped
    ``{"itemScores": [{"item", "score"}]}`` contract every shipped
    retrieval template speaks, plus a bare numeric ``score`` field."""
    if not isinstance(result, dict):
        return None
    rows = result.get("itemScores")
    if isinstance(rows, list):
        out: List[Tuple[Any, float]] = []
        for r in rows:
            if isinstance(r, dict) and isinstance(
                    r.get("score"), (int, float)):
                out.append((r.get("item"), float(r["score"])))
        return out
    s = result.get("score")
    if isinstance(s, (int, float)):
        return [(None, float(s))]
    return None


def resolve_scorecard(models: Sequence[Any]
                      ) -> Tuple[Optional[Scorecard], Optional[str]]:
    """(scorecard, reporting_reason) for a loaded model set.

    Walks the wrappers for a serialized :class:`Scorecard`; when the
    carrying wrapper also exposes its host corpus (``item_vecs``), the
    scorecard's fingerprint is validated against it — the same tripwire
    the PR-8 IVF index uses — and a mismatch degrades to reporting-only
    with an ERROR (never blocks serving)."""
    for m in models or ():
        sc = getattr(m, "quality", None)
        if not isinstance(sc, Scorecard):
            continue
        vecs = getattr(m, "item_vecs", None)
        if sc.fingerprint and vecs is not None:
            try:
                import numpy as np

                from predictionio_tpu.retrieval.ivf import (
                    corpus_fingerprint,
                )

                if corpus_fingerprint(
                        np.ascontiguousarray(vecs)) != sc.fingerprint:
                    logger.error(
                        "quality scorecard fingerprint mismatch for %r — "
                        "drift detection degrades to reporting-only "
                        "(serving continues)", type(m).__name__)
                    return None, "fingerprint_mismatch"
            except Exception:
                logger.warning("scorecard fingerprint check failed",
                               exc_info=True)
        return sc, None
    return None, "no_scorecard"


# ==========================================================================
# The engine-server facade
# ==========================================================================

class QualityMonitor:
    """The engine server's quality layer: one instance per server.

    ``observe`` is the scheduler-dispatch-boundary hook (one sampled
    append per request); ``on_generation`` re-anchors the drift detector
    on every reload/rollback swap (the scorecard rides the model
    wrapper, so baseline and model swap atomically); ``payload`` is the
    ``/quality.json`` document, including the promotion-gate verdict the
    refresh daemon's ``HttpPromoter`` polls.  With ``PIO_QUALITY=off``
    every method is an inert no-op and no instruments register."""

    def __init__(self, config: Optional[QualityConfig] = None, *,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.config = config or QualityConfig.from_env()
        self.enabled = self.config.enabled
        self._clock = clock
        self._rng = rng or random.Random()
        if not self.enabled:
            return
        reg = registry or get_registry()
        self._registry = reg
        self._lock = threading.Lock()
        self._generation = 0
        self._detector = DriftDetector(self.config, None, clock=clock)
        self.shadow = ShadowScorer(self.config, registry=reg)
        self.joiner = feedback_joiner()
        # diversity window: per-sampled-request served item lists with
        # incremental distinct/top-item counts (O(k) per sample).
        self._div_window: deque = deque()
        self._div_counts: Dict[Any, int] = {}
        self._div_slots = 0
        self._h_score = reg.histogram(
            "pio_predict_score",
            "Served top-K prediction scores (sampled; the serving side "
            "of the drift comparison).", (), buckets=SCORE_BUCKETS)
        self._m_sampled = reg.counter(
            "pio_quality_sampled_total",
            "Requests sampled into the prediction record stream.")
        self._m_empty = reg.counter(
            "pio_quality_empty_total",
            "Sampled requests whose result carried zero items.")
        self._g_drift = reg.gauge(
            "pio_quality_drift",
            "Score-distribution drift vs the training-time scorecard.",
            ("metric", "window"))
        self._g_tripped = reg.gauge(
            "pio_quality_drift_tripped",
            "1 while drift is over threshold on both windows "
            "(hysteresis-latched).")
        self._g_reporting = reg.gauge(
            "pio_quality_reporting_only",
            "1 while the drift detector runs without a trusted scorecard "
            "(missing or fingerprint-mismatched) — reporting, never "
            "gating.")
        self._g_diversity = reg.gauge(
            "pio_quality_candidate_diversity",
            "Distinct items / served item slots over the sampled window "
            "(1.0 = every slot unique; collapse → 1/window).")
        self._g_top_share = reg.gauge(
            "pio_quality_top_item_share",
            "Share of sampled served slots taken by the single most "
            "frequent item.")
        self._g_fold_share = reg.gauge(
            "pio_quality_fold_in_share",
            "Fold-in-served share of predict requests (solved+cached / "
            "requests).")
        self._g_gate = reg.gauge(
            "pio_quality_gate_rollback",
            "1 while the quality gate verdict is ROLLBACK (drift tripped "
            "or shadow divergence, with enough samples).")

    # -- sampling -----------------------------------------------------------

    def draw(self) -> float:
        """THE per-request uniform draw: shared by the prediction
        stream, shadow sampling, and the request-log sampler (ISSUE 11
        satellite: one RNG draw per request, many thresholds)."""
        return self._rng.random()

    # -- generation lifecycle ----------------------------------------------

    def on_generation(self, generation: int, models: Sequence[Any], *,
                      shadow_fn: Optional[Callable[[Any], Any]] = None,
                      prev_generation: Optional[int] = None) -> None:
        """Re-anchor on a swap (reload or rollback): fresh drift windows
        against the NEW generation's scorecard; arm shadow scoring when
        the swap retained a previous generation to score against."""
        if not self.enabled:
            return
        scorecard, reason = resolve_scorecard(models)
        if scorecard is None:
            logger.warning(
                "quality: generation %d has no usable scorecard (%s) — "
                "drift detection is reporting-only", generation, reason)
        with self._lock:
            self._generation = generation
            self._detector = DriftDetector(
                self.config, scorecard, reporting_reason=reason,
                clock=self._clock)
            self._div_window.clear()
            self._div_counts.clear()
            self._div_slots = 0
        self._g_reporting.set(1 if scorecard is None else 0)
        if shadow_fn is not None:
            self.shadow.start(generation, prev_generation, shadow_fn)
        else:
            self.shadow.stop()

    def end_shadow(self, reason: str) -> None:
        if self.enabled:
            self.shadow.stop(reason)

    # -- the dispatch-boundary hook -----------------------------------------

    def observe(self, query: Any, result: Any, generation: Optional[int],
                u: Optional[float]) -> Optional[str]:
        """Record one served request (called right where the scheduler
        hands the result back).  ``u`` is the request's shared sample
        draw; anything ≥ the sample rate costs two comparisons and
        returns.  Sampled requests append their scores to the drift
        windows, update diversity, register the serve for the feedback
        join, and (inside a canary window) enqueue for shadow scoring.
        Returns the serve id to echo as ``X-PIO-Serve-Id``, or None."""
        if not self.enabled or u is None or u >= self.config.sample:
            return None
        items = extract_result_items(result)
        if items is None:
            return None  # unscored result shape — quality stays inert
        gen = int(generation) if generation is not None \
            else self._generation
        self._m_sampled.inc()
        if not items:
            self._m_empty.inc()
        for _, score in items:
            self._h_score.observe(score)
        if items:
            # Drift feeds the RANK-1 score only: the statistic the
            # scorecard baselines (invariant to the client's num — a
            # top-3 request's score set sits structurally above a
            # top-10 one's and would fake drift on a healthy server).
            self._detector.add(max(s for _, s in items))
        ids = [i for i, _ in items if i is not None]
        if ids:
            with self._lock:
                self._div_window.append(ids)
                for i in ids:
                    self._div_counts[i] = self._div_counts.get(i, 0) + 1
                self._div_slots += len(ids)
                while len(self._div_window) > max(
                        self.config.fast_window, 1):
                    old = self._div_window.popleft()
                    self._div_slots -= len(old)
                    for i in old:
                        n = self._div_counts.get(i, 0) - 1
                        if n <= 0:
                            self._div_counts.pop(i, None)
                        else:
                            self._div_counts[i] = n
        sid = f"g{gen}-{uuid.uuid4().hex[:10]}"
        self.joiner.note_serve(sid, gen, ids)
        # Shadow rate on the SHARED draw: u is already < sample here, so
        # the threshold must be the product sample×shadow_sample — a
        # bare `u < shadow_sample` would shadow-score EVERY sampled
        # request whenever shadow_sample ≥ sample (4× the documented
        # cost at the defaults) and turn the knob dead.
        if u < self.config.sample * self.config.shadow_sample:
            self.shadow.submit(query, items, gen)
        return sid

    # -- verdict / views ----------------------------------------------------

    def _diversity(self) -> Tuple[Optional[float], Optional[float]]:
        with self._lock:
            slots = self._div_slots
            if not slots:
                return None, None
            distinct = len(self._div_counts)
            top = max(self._div_counts.values())
        return distinct / slots, top / slots

    def _fold_in_share(self) -> Optional[float]:
        served = self._registry.get("pio_fold_in_total")
        reqs = self._registry.get("pio_query_requests_total")
        if served is None or reqs is None:
            return None
        total_reqs = reqs.total()
        if not total_reqs:
            return None
        rows = served.series()
        folded = sum(v for k, v in rows.items()
                     if k and k[0] in ("solved", "cached"))
        return folded / total_reqs

    def payload(self) -> Dict[str, Any]:
        """The ``/quality.json`` document (also the promotion gate the
        refresh daemon polls)."""
        if not self.enabled:
            return {"enabled": False}
        drift = self._detector.tick()
        shadow = self.shadow.snapshot()
        diversity, top_share = self._diversity()
        fold_share = self._fold_in_share()
        sc = self._detector.baseline
        reasons = []
        drift_gates = (drift.get("tripped")
                       and not drift.get("reportingOnly"))
        if drift_gates:
            reasons.append("drift")
        if shadow.get("divergent"):
            reasons.append("shadow_divergence")
        rollback = bool(reasons) and self.config.gate
        if drift.get("reportingOnly"):
            verdict = "reporting_only"
        elif drift_gates or shadow.get("divergent"):
            verdict = "degraded"
        elif drift.get("insufficient", True) and (
                not shadow.get("active")
                or shadow.get("insufficient", True)):
            verdict = "insufficient"
        else:
            verdict = "healthy"
        # publish the gauges the fleet/status views scrape
        for metric, vals in (("psi", drift.get("psi") or {}),
                             ("kl", drift.get("kl") or {})):
            for window in ("fast", "slow"):
                v = vals.get(window)
                if v is not None:
                    self._g_drift.set(v, metric=metric, window=window)
        self._g_tripped.set(1 if drift.get("tripped") else 0)
        self._g_gate.set(1 if rollback else 0)
        if diversity is not None:
            self._g_diversity.set(diversity)
        if top_share is not None:
            self._g_top_share.set(top_share)
        if fold_share is not None:
            self._g_fold_share.set(fold_share)
        return {
            "enabled": True,
            "generation": self._generation,
            "verdict": verdict,
            "gate": {"enabled": self.config.gate,
                     "rollback": rollback,
                     "reasons": reasons},
            "drift": drift,
            "shadow": shadow,
            "feedback": self.joiner.snapshot(),
            "sampling": {
                "sample": self.config.sample,
                "shadowSample": self.config.shadow_sample,
                "sampledTotal": int(self._m_sampled.value()),
                "emptyTotal": int(self._m_empty.value()),
                "foldInShare": (round(fold_share, 4)
                                if fold_share is not None else None),
            },
            "diversity": {
                "candidateDiversity": (round(diversity, 4)
                                       if diversity is not None else None),
                "topItemShare": (round(top_share, 4)
                                 if top_share is not None else None),
            },
            "scorecard": (sc.summary() if sc is not None
                          else {"present": False,
                                "reason": self._detector.reporting_reason}),
        }

    def summary(self) -> Dict[str, Any]:
        """Compact ``/stats.json`` embed."""
        if not self.enabled:
            return {"enabled": False}
        doc = self.payload()
        return {"enabled": True,
                "verdict": doc["verdict"],
                "gateRollback": doc["gate"]["rollback"],
                "psiFast": doc["drift"].get("psi", {}).get("fast"),
                "psiSlow": doc["drift"].get("psi", {}).get("slow"),
                "shadowOverlap": doc["shadow"].get("overlapMean"),
                "sampled": doc["sampling"]["sampledTotal"]}

    def close(self) -> None:
        if self.enabled:
            self.shadow.close()


# ==========================================================================
# Fleet merge
# ==========================================================================

# Keys whose numeric values SUM across instances (counts); every other
# number takes the MAX (drift magnitudes, shares — the fleet's verdict
# must reflect the worst instance, and summing a PSI is meaningless).
_SUM_KEYS = frozenset((
    "samples", "scored", "sampledTotal", "emptyTotal", "tracked",
    "hits", "misses", "attributedOnly", "nFast", "nSlow", "n",
    "captured", "dropped",
))
# Recall fields (ISSUE 16) take the MIN: the fleet's recall IS its worst
# instance (a rotten replica hides inside a max or a mean), and the
# baseline pins to the most conservative scorecard in the set.  Flat key
# names on purpose — psi's fast/slow (drift magnitude) correctly takes
# MAX, so recall's windows must not share those key names.
_MIN_KEYS = frozenset(("recallFast", "recallSlow", "baseline"))
_VERDICT_ORDER = ("healthy", "insufficient", "reporting_only", "degraded")


def merge_quality(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet merge of N ``/quality.json`` documents.

    Walks the UNION of keys recursively, so no instance's field is ever
    silently dropped (the tier-1 schema-stability test pins this):
    counts sum, magnitudes take the worst (max), recall readings take
    the worst (MIN — a rotten replica must surface), booleans OR,
    verdicts take the worst of the ordering, strings keep the first
    non-null.
    Disabled instances are skipped; all-disabled merges to
    ``{"enabled": False}``."""
    live = [d for d in docs if isinstance(d, dict) and d.get("enabled")]
    if not live:
        return {"enabled": False, "instances": len(list(docs))}
    merged = _merge_values("", live)
    merged["enabled"] = True
    merged["instances"] = len(live)
    # hit-rate style ratios recompute from the summed parts
    fb = merged.get("feedback")
    if isinstance(fb, dict) and isinstance(fb.get("generations"), dict):
        for row in fb["generations"].values():
            if isinstance(row, dict):
                h, m = row.get("hits", 0) or 0, row.get("misses", 0) or 0
                row["hitRate"] = round(h / (h + m), 4) if h + m else None
    return merged


def _merge_values(key: str, values: List[Any]) -> Any:
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    if all(isinstance(v, dict) for v in vals):
        keys: List[str] = []
        for v in vals:
            for k in v:
                if k not in keys:
                    keys.append(k)
        return {k: _merge_values(k, [v.get(k) for v in vals])
                for k in keys}
    if all(isinstance(v, bool) for v in vals):
        return any(vals)
    if all(isinstance(v, (int, float)) and not isinstance(v, bool)
           for v in vals):
        if key in _MIN_KEYS:
            return min(vals)
        if key in _SUM_KEYS:
            return sum(vals)
        return max(vals)
    if key == "verdict":
        return max(vals, key=lambda v: _VERDICT_ORDER.index(v)
                   if v in _VERDICT_ORDER else 0)
    if all(isinstance(v, list) for v in vals):
        out: List[Any] = []
        for v in vals:
            for item in v:
                if item not in out:
                    out.append(item)
        return out
    return vals[0]
