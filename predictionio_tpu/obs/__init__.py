"""Unified observability layer (SURVEY §5.5 rebuild addition).

Three parts, one process-wide state:

- :mod:`predictionio_tpu.obs.metrics` — thread-safe Counter / Gauge /
  Histogram registry with label support and THE Prometheus text renderer
  behind every server's ``GET /metrics``.
- :mod:`predictionio_tpu.obs.trace` — span/trace API with per-request
  trace ids (``X-Request-ID``), a last-N ring buffer (``GET
  /traces.json``), JSONL export (``PIO_TRACE_FILE``), and slow-request
  logging (``PIO_SLOW_REQUEST_MS``).
- :mod:`predictionio_tpu.obs.pipeline` — training-loop probe decomposing
  the feeder→device pipeline into host-wait / H2D / device-step.
- :mod:`predictionio_tpu.obs.runtime` — runtime introspection below the
  request/training layer: XLA compile tracking, device-memory telemetry,
  the per-step timeline ring, and trace-ring event publication.
- :mod:`predictionio_tpu.obs.profiler` — on-demand bounded
  ``jax.profiler`` capture behind ``POST /admin/profile`` and
  ``pio profile``.
- :mod:`predictionio_tpu.obs.waterfall` — per-request serving stage
  decomposition (``pio_serve_stage_ms{stage}`` + exemplars + the
  ``PIO_REQUEST_LOG`` wide-event JSONL).
- :mod:`predictionio_tpu.obs.slo` — availability/latency SLOs,
  multi-window burn rates, the ``/ready`` degradation verdict.
- :mod:`predictionio_tpu.obs.fleet` — Prometheus-text parsing and the
  type-correct multi-instance merge behind ``/fleet.json`` /
  ``pio status --fleet``.
- :mod:`predictionio_tpu.obs.quality` — model-quality observability:
  sampled prediction stream, scorecard drift (PSI/KL), shadow-scored
  canaries, feedback-joined online hit-rate, and the ``/quality.json``
  promotion gate.

stdlib-only on import: safe from the CLI, the servers, and the data layer
without touching jax/numpy.
"""

from predictionio_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from predictionio_tpu.obs.pipeline import PipelineProbe
from predictionio_tpu.obs.runtime import (
    CompileTracker,
    DeviceMemorySampler,
    StepTimeline,
    get_compile_tracker,
    get_memory_sampler,
    get_timeline,
    publish_event,
    reset_runtime,
    set_timeline,
    start_runtime_introspection,
    track_compiles,
)
from predictionio_tpu.obs.trace import (
    Span,
    TraceRecorder,
    attach_event,
    current_span,
    current_trace_id,
    get_recorder,
    new_trace_id,
    sanitize_trace_id,
    set_recorder,
    slow_request_ms,
    span,
    trace,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "PipelineProbe",
    "CompileTracker",
    "DeviceMemorySampler",
    "StepTimeline",
    "get_compile_tracker",
    "get_memory_sampler",
    "get_timeline",
    "publish_event",
    "set_timeline",
    "start_runtime_introspection",
    "track_compiles",
    "Span",
    "TraceRecorder",
    "attach_event",
    "current_span",
    "current_trace_id",
    "get_recorder",
    "new_trace_id",
    "sanitize_trace_id",
    "set_recorder",
    "slow_request_ms",
    "span",
    "trace",
    "phase",
    "reset_observability",
]

import contextlib as _contextlib


@_contextlib.contextmanager
def phase(name: str, **attrs):
    """Span + per-phase duration histogram in one context manager.

    The workflow's named phases (datasource / prepare / train / persist)
    show up both in the trace tree AND as ``pio_train_phase_ms{phase=...}``
    series, so a dashboard can watch phase drift without trace plumbing.
    (The metric name is a literal by design — tools/lint_metrics.py
    keeps every registered name statically checkable.)
    """
    hist = get_registry().histogram(
        "pio_train_phase_ms", "Workflow phase duration by phase name.",
        ("phase",))
    with span(name, **attrs) as s:
        try:
            yield s
        finally:
            # record crashed phases too — the runs most worth seeing
            s.finish()
            hist.observe(s.duration_ms or 0.0, phase=name)


def reset_observability() -> None:
    """Fresh registry + empty trace ring + empty timeline/peaks (test
    isolation; see conftest)."""
    get_registry().reset()
    get_recorder().clear()
    reset_runtime()
    # A test that drives the engine's pio_handle directly (no transport
    # driver) arms the request waterfall but nothing finalizes it — drop
    # the leaked collector so the NEXT test's contextvar view is clean.
    from predictionio_tpu.obs import waterfall as _waterfall
    _waterfall.deactivate()
    # The feedback joiner is process-global (engine notes serves, event
    # server joins) — drop it with the registry its counters lived in.
    from predictionio_tpu.obs.quality import reset_quality
    reset_quality()
