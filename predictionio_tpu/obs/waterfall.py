"""Per-request serving latency waterfall (ISSUE 9 tentpole part 1).

``pio_request_ms`` said ONE number about a request that now crosses six
subsystems (admission queue → micro-batch window → generation snapshot →
retrieval rung → XLA dispatch → transport shed).  This module carries a
per-stage decomposition on every ``/queries.json`` request:

========== ==============================================================
stage       meaning
========== ==============================================================
ingress     transport receipt → bind start (socket body read, trace
            setup, routing, pre-admission deadline check)
queue_wait  admission → a batcher gather picked the entry up
batch_wait  gather pickup → dispatch start (window / deadline-close wait)
bind        JSON parse + query-dataclass bind (handler thread)
cache       result-cache key canonicalization + lookup (ISSUE 20; on a
            hit this is the ONLY serving stage — queue/dispatch never
            run — so attribution stays honest about the fast path)
dispatch    the ONE vectorized model dispatch the batch shared
resume      dispatch done → the handler thread actually running again
            (event wake-up under GIL/thread contention)
retrieval   corpus top-K inside the dispatch (rung-tagged; ⊂ dispatch,
            NOT additive with it)
serialize   result → JSON bytes (the ``http.respond`` write path)
shed_check  scheduler return → the respond write (span unwind, late-shed
            verdict, stats hooks, response-header assembly)
========== ==============================================================

Three consumers, one collector:

- ``pio_serve_stage_ms{stage}`` histogram family, every bucket carrying
  an exemplar trace id that resolves via ``/traces.json?request_id=``;
- a ``waterfall`` event attached to the request's own span tree;
- an opt-in wide-event JSONL (``PIO_REQUEST_LOG=path``): one
  self-contained line per request for offline attribution
  (``tools/attribute_serve.py``).

Thread model: the handler thread owns the :class:`Waterfall` (contextvar
``begin_request``); the batcher thread stamps its stages through the
``Pending`` hand-off, and the retrieval facade — which runs on the
batcher thread with no request context — records into a per-DISPATCH
sink (:func:`dispatch_sink`) that the batcher then merges into every
member.  All writes go through one lock; a waiter that walked (deadline)
closes the collector, after which late stamps are dropped instead of
racing the final observation.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from predictionio_tpu.obs.metrics import get_registry

logger = logging.getLogger(__name__)

__all__ = [
    "ATTESTED_STAGES",
    "SERVE_STAGES",
    "WALL_STAGES",
    "Waterfall",
    "active_sample_u",
    "begin_request",
    "current_waterfall",
    "dispatch_sink",
    "note_transport_start",
    "record_stage",
    "stage_histogram",
    "transport_start",
]

SERVE_STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
                "dispatch", "resume", "retrieval", "serialize",
                "shed_check")
# The additive stages: their sum should reconcile with the request's
# total wall (retrieval is a sub-component of dispatch; resume is the
# handler thread's post-dispatch wake-up — event set → actually running
# again under GIL/thread contention).
WALL_STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
               "dispatch", "resume", "serialize", "shed_check")
# The stages the server-attested X-PIO-Server-Ms wall CONTAINS: the
# attestation header is read before the response is written (headers
# must be assembled first), so serialize — the respond/socket write —
# lies outside it by construction.  Reconciling against the attestation
# must sum exactly these.
ATTESTED_STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
                   "dispatch", "resume", "shed_check")


def stage_histogram(registry=None):
    """THE per-stage latency family (get-or-create on the registry)."""
    return (registry or get_registry()).histogram(
        "pio_serve_stage_ms",
        "Per-request serving latency by pipeline stage "
        "(retrieval is a sub-stage of dispatch, not additive).",
        ("stage",))


class Waterfall:
    """One request's stage collector (thread-safe, close-once)."""

    __slots__ = ("stages", "attrs", "_lock", "_closed", "_marks",
                 "sample_u")

    def __init__(self):
        self.stages: Dict[str, float] = {}
        self.attrs: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._marks: Dict[str, float] = {}
        # THE request's shared uniform sample draw (ISSUE 11): set once
        # by the engine handler; the wide-event log sampler
        # (PIO_REQUEST_LOG_SAMPLE) and the prediction record stream
        # (PIO_QUALITY_SAMPLE) each compare it against their own rate —
        # one RNG draw per request, many thresholds.
        self.sample_u: Optional[float] = None

    def attr(self, name: str, default: Any = None) -> Any:
        """One attribute under the lock (the engine handler reads the
        generation the batcher stamped onto the dispatch)."""
        with self._lock:
            return self.attrs.get(name, default)

    def note(self, **attrs) -> None:
        """Attach attributes without a stage stamp (the serve id rides
        here into the wide event AND to the transport's response-header
        hook)."""
        with self._lock:
            if not self._closed:
                self.attrs.update(attrs)

    def mark(self, name: str) -> None:
        """Record a wall-clock boundary (``time.perf_counter``) another
        layer closes into a stage later — the engine handler marks
        ``handler_done`` when the scheduler hands the result back, and
        the transport driver stamps ``shed_check`` from that mark so the
        span-unwind / stats-hook segment between them is accounted."""
        with self._lock:
            if not self._closed:
                self._marks[name] = time.perf_counter()

    def take_mark(self, name: str) -> Optional[float]:
        with self._lock:
            return self._marks.pop(name, None)

    def stamp(self, stage: str, ms: float, **attrs) -> None:
        """Add ``ms`` to a stage (accumulates: a retried dispatch bills
        both attempts).  Dropped once the request finalized."""
        with self._lock:
            if self._closed:
                return
            self.stages[stage] = self.stages.get(stage, 0.0) + float(ms)
            if attrs:
                self.attrs.update(attrs)

    def merge(self, stages: Dict[str, float], **attrs) -> None:
        with self._lock:
            if self._closed:
                return
            for k, v in stages.items():
                self.stages[k] = self.stages.get(k, 0.0) + float(v)
            if attrs:
                self.attrs.update(attrs)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.stages)

    def export(self) -> "tuple[Dict[str, float], Dict[str, Any]]":
        """(stages, attrs) copy — the batcher reads its per-dispatch sink
        once and fans the result out to every member request."""
        with self._lock:
            return dict(self.stages), dict(self.attrs)

    def finalize(self, *, trace_id: Optional[str], status: int,
                 total_ms: float, attested_ms: Optional[float] = None,
                 registry=None) -> Dict[str, Any]:
        """Close the collector and publish: histogram observations (with
        the request's trace id as each bucket's exemplar) + the wide
        event to ``PIO_REQUEST_LOG``.  ``attested_ms`` is the SAME
        reading the ``X-PIO-Server-Ms`` header carried, recorded so the
        wide event is self-contained for the stage-sum-vs-attestation
        reconciliation.  Returns the wide-event document (the caller may
        attach it to the request span)."""
        with self._lock:
            if self._closed:
                return {}
            self._closed = True
            stages = dict(self.stages)
            attrs = dict(self.attrs)
        hist = stage_histogram(registry)
        for stage, ms in stages.items():
            hist.observe(ms, exemplar=trace_id, stage=stage)
        doc: Dict[str, Any] = {
            "ts": round(time.time(), 3),
            "traceId": trace_id,
            "status": int(status),
            "totalMs": round(total_ms, 3),
            "stages": {k: round(v, 3) for k, v in stages.items()},
            "stageSumMs": round(
                sum(stages.get(s, 0.0) for s in WALL_STAGES), 3),
            "attestedSumMs": round(
                sum(stages.get(s, 0.0) for s in ATTESTED_STAGES), 3),
            **{k: v for k, v in attrs.items()},
        }
        if attested_ms is not None:
            doc["serverMs"] = round(attested_ms, 3)
        _request_log_write(doc, self.sample_u)
        return doc


# -- context plumbing -------------------------------------------------------

_current: contextvars.ContextVar[Optional[Waterfall]] = \
    contextvars.ContextVar("pio_waterfall", default=None)
# Per-DISPATCH sink: set by the batcher around the model dispatch so
# library code below it (retrieval facade) can record stages without any
# notion of the member requests sharing the dispatch.
_sink: contextvars.ContextVar[Optional[Waterfall]] = \
    contextvars.ContextVar("pio_waterfall_sink", default=None)
# The transport driver's request-receipt wall clock (perf_counter):
# noted at the top of BaseHandler.dispatch — BEFORE any collector exists
# — so the engine handler can stamp ``ingress`` (receipt → bind) when it
# arms the waterfall mid-handle.  Overwritten per request on keep-alive
# handler threads.
_transport_t0: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("pio_waterfall_t0", default=None)


def note_transport_start(t0: float) -> None:
    _transport_t0.set(t0)


def transport_start() -> Optional[float]:
    return _transport_t0.get()


@contextlib.contextmanager
def begin_request():
    """Attach a fresh :class:`Waterfall` to the current context (the
    handler thread's request scope)."""
    wf = Waterfall()
    token = _current.set(wf)
    try:
        yield wf
    finally:
        _current.reset(token)


def activate() -> Waterfall:
    """Unscoped variant of :func:`begin_request`: the engine handler
    arms the collector mid-``pio_handle`` and the TRANSPORT driver
    (``BaseHandler.dispatch``) finalizes it after the response is
    written — the serialize/shed_check stages live outside the handler's
    own scope, so a ``with`` block there would strip the contextvar too
    early.  :func:`deactivate` clears it (keep-alive connections reuse
    the handler thread; a leaked collector would swallow the NEXT
    request's stamps)."""
    wf = Waterfall()
    _current.set(wf)
    return wf


def deactivate() -> None:
    _current.set(None)


def current_waterfall() -> Optional[Waterfall]:
    return _current.get()


@contextlib.contextmanager
def dispatch_sink(wf: Waterfall):
    """Route :func:`record_stage` calls in this context into ``wf`` (the
    batcher's per-dispatch collector)."""
    token = _sink.set(wf)
    try:
        yield wf
    finally:
        _sink.reset(token)


def active_sample_u() -> Optional[float]:
    """The active collector's shared per-request sample draw (ISSUE 11)
    — dispatch sink first (the batcher stamps the members' draw onto it),
    else the request's own waterfall.  None when unsampled or outside any
    request, so samplers below the facade (retrieval recall capture) cost
    one contextvar read on the common path."""
    wf = _sink.get() or _current.get()
    return wf.sample_u if wf is not None else None


def record_stage(stage: str, ms: float, **attrs) -> None:
    """Stamp a stage onto whatever collector is active — the dispatch
    sink first (batcher thread), else the request's own waterfall.  A
    no-op outside both, so instrumented library code costs one
    contextvar read on un-instrumented paths."""
    wf = _sink.get() or _current.get()
    if wf is not None:
        wf.stamp(stage, ms, **attrs)


# -- wide-event request log (PIO_REQUEST_LOG) -------------------------------

_log_lock = threading.Lock()


def _log_sample_rate() -> float:
    """``PIO_REQUEST_LOG_SAMPLE`` (default 1.0 = every request): the
    wide-event log's share of requests.  Read per write, like the path —
    an operator can turn a hot server's log down live."""
    raw = os.environ.get("PIO_REQUEST_LOG_SAMPLE")
    if raw is None or not str(raw).strip():
        return 1.0
    try:
        return min(max(float(raw), 0.0), 1.0)
    except (TypeError, ValueError):
        return 1.0


def _request_log_write(doc: Dict[str, Any],
                       sample_u: Optional[float] = None) -> None:
    path = os.environ.get("PIO_REQUEST_LOG")
    if not path:
        return
    rate = _log_sample_rate()
    if rate < 1.0:
        # One sampling decision per request: reuse the handler's shared
        # draw when it made one (so the wide event and the prediction
        # stream describe the SAME sampled population), else draw here.
        import random as _random

        u = sample_u if sample_u is not None else _random.random()
        if u >= rate:
            return
    line = json.dumps(doc, separators=(",", ":"))
    try:
        # Handle not cached: the path may change/rotate live (same
        # discipline as PIO_TRACE_FILE).
        with _log_lock, open(path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
    except OSError:
        logger.exception("cannot append request log to %s", path)
