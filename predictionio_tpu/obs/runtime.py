"""Runtime introspection: XLA compile tracking, device-memory telemetry,
and the per-step pipeline timeline ring.

PR 1 made requests and training phases observable; this module opens the
layer *below* — the JAX runtime — following the always-on/low-overhead
model of Google-Wide Profiling (Ren et al., IEEE Micro 2010):

- :class:`CompileTracker` wraps jit entry points and exports
  ``pio_xla_compile_total{fn}`` / ``pio_xla_compile_ms{fn}``; a function
  that compiles more than ``PIO_COMPILE_WARN_THRESHOLD`` times (default
  3) logs a structured shape-churn warning.  Every compile also lands in
  the PR-1 trace ring (:func:`publish_event`), so a slow request or
  training step can be explained by "recompiled here".
- :class:`DeviceMemorySampler` polls ``device.memory_stats()`` (and a
  ``jax.live_arrays()`` fallback for backends like CPU that report no
  allocator stats) into ``pio_device_mem_bytes{device,kind}`` gauges with
  per-train-run peak tracking (``pio_device_mem_peak_bytes{device}``),
  surfaced by ``pio status``.  The clock/devices are injectable (same
  discipline as ``resilience/policy.py``) so tests run on fakes with no
  wall sleeps.
- :class:`StepTimeline` is a process-wide ring of per-step pipeline phase
  decompositions (host_wait / h2d / device_wait / device_step, fed by
  ``obs.pipeline.PipelineProbe``), served at ``/timeline.json``,
  exportable as Chrome-trace JSON, and consumed by
  ``tools/attribute_gap.py`` to attribute the feeder-vs-realized gap.

Like the rest of ``obs``, importing this module never imports jax: all
jax touches are lazy and degrade to no-ops when jax is absent — the
event server keeps its jax-free footprint.
"""

from __future__ import annotations

import collections
import functools
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry
from predictionio_tpu.obs.trace import (
    Span,
    TraceRecorder,
    current_span,
    current_trace_id,
    get_recorder,
    new_trace_id,
)

logger = logging.getLogger(__name__)

__all__ = [
    "publish_event",
    "CompileTracker",
    "get_compile_tracker",
    "track_compiles",
    "DeviceMemorySampler",
    "get_memory_sampler",
    "StepTimeline",
    "get_timeline",
    "set_timeline",
    "start_runtime_introspection",
    "reset_runtime",
]


# -- trace-ring events -------------------------------------------------------

def publish_event(name: str, *, recorder: Optional[TraceRecorder] = None,
                  **attrs) -> None:
    """Publish a zero-duration annotation into the trace ring.

    Inside an active trace the event attaches as a child span of the
    innermost open span — a request that triggered a recompile (or hit a
    breaker transition, or spilled) carries the evidence in its own span
    tree.  Outside any trace it records as a standalone single-span trace
    so the ring still shows runtime incidents with their wall time.
    """
    ev = Span(name, attrs)
    ev.duration_ms = 0.0
    parent = current_span()
    if parent is not None:
        parent.children.append(ev)
        return
    (recorder or get_recorder()).record(
        current_trace_id() or new_trace_id(), ev)


# -- XLA compile tracking ----------------------------------------------------

def _jit_cache_size(jitted: Any) -> Optional[int]:
    """Compiled-variant count of a ``jax.jit`` wrapper (None: unknowable)."""
    f = getattr(jitted, "_cache_size", None)
    if f is None:
        return None
    try:
        return int(f())
    except Exception:
        return None


_trace_state_clean: Optional[Callable[[], bool]] = None


def _outside_jax_trace() -> bool:
    """True unless we are inside jax tracing (a wrapped jit called from an
    outer jit inlines — its cache growth is not an independent compile)."""
    global _trace_state_clean
    if _trace_state_clean is None:
        try:
            from jax.core import trace_state_clean as f  # type: ignore
        except Exception:
            def f() -> bool:
                return True
        _trace_state_clean = f
    try:
        return _trace_state_clean()
    except Exception:
        return True


class CompileTracker:
    """Counts XLA compilations per tracked jit entry point.

    Instruments resolve from the process registry at record time (not
    construction), so a test-isolation registry reset never strands the
    tracker on unregistered series.  Detection is cache-growth across a
    call: a call after which the jit wrapper holds more compiled variants
    than before paid a compilation, and the call's wall time bounds the
    compile time (trace+lower+compile dominate such calls).
    """

    def __init__(self, warn_threshold: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self._registry = registry
        self._clock = clock
        self._env_threshold = warn_threshold is None
        self.warn_threshold = (self._read_threshold()
                               if warn_threshold is None
                               else int(warn_threshold))

    @staticmethod
    def _read_threshold() -> int:
        try:
            return int(os.environ.get("PIO_COMPILE_WARN_THRESHOLD", "3"))
        except ValueError:
            return 3

    def _reg(self) -> MetricsRegistry:
        return self._registry or get_registry()

    def _counter(self):
        return self._reg().counter(
            "pio_xla_compile_total",
            "XLA compilations observed per tracked jit entry point.",
            ("fn",))

    def _hist(self):
        return self._reg().histogram(
            "pio_xla_compile_ms",
            "Wall time of calls that triggered an XLA compilation.",
            ("fn",))

    def touch(self) -> None:
        """Register the instruments so ``/metrics`` exposes them from t=0."""
        self._counter()
        self._hist()

    def record(self, fn: str, duration_ms: float) -> None:
        """One observed compilation of ``fn`` taking ``duration_ms``."""
        c = self._counter()
        c.inc(fn=fn)
        self._hist().observe(duration_ms, fn=fn)
        publish_event("xla.compile", fn=fn,
                      durationMs=round(float(duration_ms), 3))
        n = int(c.value(fn=fn))
        threshold = (self._read_threshold() if self._env_threshold
                     else self.warn_threshold)
        if n > threshold:
            # Shape churn: the same function keeps recompiling — varying
            # shapes or unhashed static args defeat the jit cache.
            logger.warning(
                "shape churn: jit fn %r compiled %d times "
                "(threshold %d, PIO_COMPILE_WARN_THRESHOLD); recurring "
                "recompilation usually means varying input shapes or "
                "non-canonical static args", fn, n, threshold)

    def wrap(self, fn_name: str, jitted: Callable) -> Callable:
        """Proxy a jitted callable; cache growth across a call = compile."""
        tracker = self

        @functools.wraps(jitted)
        def wrapper(*args, **kwargs):
            if not _outside_jax_trace():
                return jitted(*args, **kwargs)
            before = _jit_cache_size(jitted)
            t0 = tracker._clock()
            out = jitted(*args, **kwargs)
            if before is not None:
                after = _jit_cache_size(jitted)
                if after is not None and after > before:
                    tracker.record(fn_name, (tracker._clock() - t0) * 1e3)
            return out

        wrapper.__wrapped__ = jitted
        return wrapper


_compile_tracker = CompileTracker()


def get_compile_tracker() -> CompileTracker:
    """THE process compile tracker (models wrap their jit steps on it)."""
    return _compile_tracker


def track_compiles(fn_name: str) -> Callable[[Callable], Callable]:
    """Decorator form: ``step = track_compiles("model.step")(jax.jit(f))``."""
    def deco(jitted: Callable) -> Callable:
        return get_compile_tracker().wrap(fn_name, jitted)
    return deco


# -- device-memory telemetry -------------------------------------------------

def _default_devices() -> Sequence[Any]:
    """Local jax devices — ONLY when jax is already loaded in this process
    (a jax-free event server must not pay a jax import for telemetry)."""
    if "jax" not in sys.modules:
        return ()
    import jax

    return jax.local_devices()


def _default_live_arrays() -> Sequence[Any]:
    if "jax" not in sys.modules:
        return ()
    import jax

    return jax.live_arrays()


class DeviceMemorySampler:
    """Background device-memory poller over the shared registry.

    Exports every numeric key of ``device.memory_stats()`` as
    ``pio_device_mem_bytes{device,kind}`` (kind = the stats key, e.g.
    ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``) plus a
    ``live_bytes`` / ``live_arrays`` aggregate from ``jax.live_arrays()``
    for backends whose allocator reports nothing (CPU).  Tracks the peak
    ``bytes_in_use`` per device since the last :meth:`reset_peak` —
    ``run_train`` resets at run start, so the gauge IS the train run's
    peak.  ``devices_fn`` / ``live_arrays_fn`` / ``clock`` are injectable
    so tests sample fakes with no wall sleeps; the poll thread is started
    only via :meth:`start` and ticks every ``interval_s`` (env
    ``PIO_MEM_SAMPLE_INTERVAL_S``, default 10; <= 0 disables the thread,
    :meth:`sample_once` stays callable).
    """

    def __init__(self, interval_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None,
                 devices_fn: Callable[[], Sequence[Any]] = _default_devices,
                 live_arrays_fn: Callable[[], Sequence[Any]]
                 = _default_live_arrays,
                 clock: Callable[[], float] = time.monotonic):
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get("PIO_MEM_SAMPLE_INTERVAL_S", "10"))
            except ValueError:
                interval_s = 10.0
        self.interval_s = float(interval_s)
        self._registry = registry
        self._devices_fn = devices_fn
        self._live_arrays_fn = live_arrays_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._peaks: Dict[str, float] = {}
        self._peak_since: float = clock()
        # HBM headroom guardrail (one warning per device per peak window):
        # prefetch depth x donated buffers changes the training memory
        # profile, so the per-run peak is checked against the device
        # bytes limit every sample.
        self._hbm_warned: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _reg(self) -> MetricsRegistry:
        return self._registry or get_registry()

    @staticmethod
    def _warn_fraction() -> float:
        """``PIO_HBM_WARN_FRACTION`` (default 0.9): warn when a train
        run's peak ``bytes_in_use`` exceeds this fraction of the device
        ``bytes_limit``.  <= 0 disables the check."""
        try:
            return float(os.environ.get("PIO_HBM_WARN_FRACTION", "0.9"))
        except ValueError:
            return 0.9

    def _headroom_counter(self):
        return self._reg().counter(
            "pio_hbm_headroom_warn_total",
            "Times a train-run memory peak crossed the HBM headroom "
            "warning fraction (PIO_HBM_WARN_FRACTION of bytes_limit).",
            ("device",))

    def _gauges(self):
        reg = self._reg()
        return (reg.gauge(
            "pio_device_mem_bytes",
            "Device memory by device and kind (memory_stats keys; "
            "live_bytes/live_arrays fall back to jax.live_arrays()).",
            ("device", "kind")),
            reg.gauge(
            "pio_device_mem_peak_bytes",
            "Peak bytes_in_use per device since the last peak reset "
            "(run_train resets at run start).", ("device",)))

    def touch(self) -> None:
        self._gauges()
        self._headroom_counter()

    @staticmethod
    def _label(device: Any) -> str:
        return f"{getattr(device, 'platform', 'dev')}:" \
               f"{getattr(device, 'id', 0)}"

    def sample_once(self) -> Dict[str, Dict[str, float]]:
        """Poll every device once; returns {device: {kind: value}}."""
        gauge, peak_gauge = self._gauges()
        out: Dict[str, Dict[str, float]] = {}
        try:
            devices = list(self._devices_fn())
        except Exception:
            logger.debug("device enumeration failed", exc_info=True)
            return out
        for d in devices:
            label = self._label(d)
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            row: Dict[str, float] = {}
            for k, v in (stats or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    gauge.set(float(v), device=label, kind=str(k))
                    row[str(k)] = float(v)
            if row:
                out[label] = row
        # live-array fallback ONLY for devices whose allocator reported
        # nothing (CPU): a TPU train process with tens of thousands of
        # live arrays must not pay an O(arrays) walk per tick on top of
        # memory_stats().
        if len(out) < len(devices):
            self._sample_live_arrays(gauge, out,
                                     skip=frozenset(out))
        frac = self._warn_fraction()
        warn: List[tuple] = []
        with self._lock:
            for label, row in out.items():
                in_use = row.get("bytes_in_use", row.get("live_bytes"))
                if in_use is None:
                    continue
                # Deliberately NOT folding the allocator's
                # peak_bytes_in_use in: that key is monotone since
                # allocator creation and would defeat reset_peak() —
                # this window is the max of OUR samples (it can
                # undershoot a spike between ticks; the lifetime peak
                # stays visible as its own kind gauge).
                peak = max(self._peaks.get(label, 0.0), in_use)
                self._peaks[label] = peak
                peak_gauge.set(peak, device=label)
                # HBM headroom guardrail: the peak against the allocator
                # limit, once per device per peak window (run_train's
                # reset_peak re-arms it).
                limit = row.get("bytes_limit")
                if (frac > 0 and limit and peak > frac * limit
                        and label not in self._hbm_warned):
                    self._hbm_warned.add(label)
                    warn.append((label, peak, limit))
        for label, peak, limit in warn:
            self._headroom_counter().inc(device=label)
            logger.warning(
                "HBM headroom: device %s train-run peak %.0f MiB is "
                "%.1f%% of its %.0f MiB limit (warn fraction %.2f, "
                "PIO_HBM_WARN_FRACTION) — reduce PIO_PREFETCH_DEPTH, "
                "the batch size, or the model/table sharding footprint "
                "before the allocator OOMs",
                label, peak / 2**20, 100.0 * peak / limit,
                limit / 2**20, frac)
        return out

    def _sample_live_arrays(self, gauge, out, skip=frozenset()) -> None:
        """live-array aggregate per device (the stats-less-backend
        fallback); ``skip`` holds devices the allocator already covered."""
        try:
            arrays = self._live_arrays_fn()
        except Exception:
            return
        agg: Dict[str, List[float]] = {}
        for a in arrays:
            try:
                devs = a.devices() if callable(getattr(a, "devices", None)) \
                    else [getattr(a, "device", None)]
                nbytes = float(getattr(a, "nbytes", 0) or 0)
            except Exception:
                continue
            for d in devs or ():
                if d is None:
                    continue
                label = self._label(d)
                if label not in skip:
                    row = agg.setdefault(label, [0.0, 0.0])
                    row[0] += nbytes
                    row[1] += 1
                break  # attribute fully-replicated arrays once
        for label, (nbytes, count) in agg.items():
            gauge.set(nbytes, device=label, kind="live_bytes")
            gauge.set(count, device=label, kind="live_arrays")
            row = out.setdefault(label, {})
            row["live_bytes"] = nbytes
            row["live_arrays"] = count

    def peaks(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._peaks)

    def headroom_exceeded(self, fraction: Optional[float] = None) -> bool:
        """Train-run PEAK ``bytes_in_use`` (since ``reset_peak``, folded
        with one fresh sample) checked against the HBM headroom
        guardrail: True when any device's peak crosses ``fraction``
        (default ``PIO_HBM_WARN_FRACTION``) of its allocator
        ``bytes_limit``.  The fusion/batch autotuner's probe — it
        decides at round boundaries, i.e. in the trough BETWEEN windows
        (and the host runs ahead of the device), so the instantaneous
        sample routinely misses the mid-scan peak the background
        sampler saw; deciding on the trough would grow straight past
        the limit into an OOM.  Backends reporting no limit (CPU
        live-array fallback) can never push back and return False."""
        frac = self._warn_fraction() if fraction is None else float(fraction)
        if frac <= 0:
            return False
        rows = self.sample_once()  # also folds this sample into _peaks
        with self._lock:
            peaks = dict(self._peaks)
        for label, row in rows.items():
            in_use = row.get("bytes_in_use")
            limit = row.get("bytes_limit")
            peak = max(in_use or 0.0, peaks.get(label, 0.0))
            if peak and limit and peak > frac * limit:
                return True
        return False

    def reset_peak(self) -> None:
        """Start a fresh peak window (run_train calls this at run start)."""
        with self._lock:
            self._peaks.clear()
            self._hbm_warned.clear()
            self._peak_since = self._clock()

    # -- background thread --------------------------------------------------

    def start(self) -> bool:
        """Start the poll thread (idempotent); False when disabled."""
        if self.interval_s <= 0:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pio-mem-sampler", daemon=True)
            self._thread.start()
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                logger.exception("device-memory sample failed")

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        self._thread = None


_memory_sampler = DeviceMemorySampler()


def get_memory_sampler() -> DeviceMemorySampler:
    """THE process device-memory sampler."""
    return _memory_sampler


# -- step timeline ring ------------------------------------------------------

class StepTimeline:
    """Ring of per-step pipeline phase decompositions, per model.

    Each record is one training iteration's wall decomposition as
    measured by ``PipelineProbe`` (host_wait → h2d → device_wait on the
    host lane; device_step overlapped on the device lane).  Served at
    ``/timeline.json`` and exportable as Chrome-trace JSON (load in
    ``chrome://tracing`` / Perfetto).  Ring size: ``PIO_TIMELINE_RING``
    (records, default 2048).
    """

    PHASES = ("host_wait", "h2d", "h2d_overlap", "dispatch",
              "device_wait", "device_step")
    # host-lane phases whose sum approximates the iteration's wall time.
    # h2d_overlap is deliberately NOT here: prefetched staging runs under
    # device compute (data/prefetch.py) and costs the step loop nothing.
    # dispatch IS here: the step call's own wall — on synchronous-
    # dispatch backends (CPU with donated buffers) it carries the
    # execution itself, and before ISSUE 7 it hid between probe points.
    WALL_PHASES = ("host_wait", "h2d", "dispatch", "device_wait")

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PIO_TIMELINE_RING", "2048"))
            except ValueError:
                capacity = 2048
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(capacity), 1))
        self._seq = 0

    def record(self, model: str, *, host_wait_ms: float = 0.0,
               h2d_ms: float = 0.0, h2d_overlap_ms: float = 0.0,
               dispatch_ms: float = 0.0,
               device_wait_ms: float = 0.0,
               device_step_ms: float = 0.0, examples: int = 0,
               start_s: Optional[float] = None,
               dispatch_s: Optional[float] = None,
               staged_s: Optional[float] = None,
               step: Optional[int] = None,
               fused_steps: int = 1) -> None:
        if start_s is None:
            start_s = time.time()
        rec = {
            "model": model,
            "startS": round(float(start_s), 6),
            "hostWaitMs": round(float(host_wait_ms), 4),
            "h2dMs": round(float(h2d_ms), 4),
            "h2dOverlapMs": round(float(h2d_overlap_ms), 4),
            "dispatchMs": round(float(dispatch_ms), 4),
            "deviceWaitMs": round(float(device_wait_ms), 4),
            "deviceStepMs": round(float(device_step_ms), 4),
            "examples": int(examples),
            # Optimizer steps this ONE record (= one dispatch) covers: a
            # K-fused lax.scan window writes K — the per-dispatch wall is
            # attributable to K steps, and attribute_gap reads the mean
            # fusion depth off the summary.
            "fusedSteps": max(int(fused_steps), 1),
        }
        # True dispatch / staging-end wall clocks (when known): the
        # Chrome export draws the device and prefetch lanes from these
        # instead of approximating from the step start.
        if dispatch_s is not None:
            rec["dispatchS"] = round(float(dispatch_s), 6)
        if staged_s is not None:
            rec["stagedS"] = round(float(staged_s), 6)
        with self._lock:
            self._seq += 1
            rec["step"] = int(step if step is not None else self._seq)
            self._ring.append(rec)

    def recent(self, n: int = 256,
               model: Optional[str] = None) -> List[Dict[str, Any]]:
        """Last ``n`` records, most recent first (optionally one model)."""
        with self._lock:
            items = list(self._ring)
        if model is not None:
            items = [r for r in items if r["model"] == model]
        return items[::-1][:max(n, 0)]

    def models(self) -> List[str]:
        with self._lock:
            return sorted({r["model"] for r in self._ring})

    def summary(self, model: Optional[str] = None) -> Dict[str, Any]:
        """Aggregate phase totals/shares — the attribute_gap input.

        ``phase_share`` is each host-lane phase's share of the summed
        host-lane wall (host_wait + h2d + device_wait): the decomposition
        of where the training loop's time actually went.
        """
        with self._lock:
            items = [r for r in self._ring
                     if model is None or r["model"] == model]
        totals = {p: 0.0 for p in self.PHASES}
        examples = 0
        steps = 0
        for r in items:
            totals["host_wait"] += r["hostWaitMs"]
            totals["h2d"] += r["h2dMs"]
            totals["h2d_overlap"] += r.get("h2dOverlapMs", 0.0)
            totals["dispatch"] += r.get("dispatchMs", 0.0)
            totals["device_wait"] += r["deviceWaitMs"]
            totals["device_step"] += r["deviceStepMs"]
            examples += r["examples"]
            steps += max(int(r.get("fusedSteps", 1)), 1)
        wall = sum(totals[p] for p in self.WALL_PHASES)
        shares = {p: (totals[p] / wall if wall > 0 else 0.0)
                  for p in self.WALL_PHASES}
        return {
            "model": model,
            # Optimizer steps vs dispatches: with K-step fusion one
            # record covers K steps, so the pair exposes the mean
            # fusion depth attribute_gap reports.
            "steps": steps,
            "dispatches": len(items),
            "fuse_steps": round(steps / len(items), 2) if items else 0.0,
            "examples": examples,
            "phase_ms": {p: round(v, 3) for p, v in totals.items()},
            "phase_share": {p: round(v, 4) for p, v in shares.items()},
        }

    def to_chrome_trace(self, n: int = 2048,
                        model: Optional[str] = None) -> Dict[str, Any]:
        """Chrome-trace-format export (``?format=chrome``).

        Host-lane phases lay out sequentially from each step's start.
        The device step rides a second lane from the recorded dispatch
        timestamp (``dispatchS``) when present — the true h2d/compute
        overlap — falling back to the step start for records written
        before dispatch stamping.  Prefetched staging (``h2dOverlapMs``)
        draws on a third lane, ending when the batch left the prep
        thread (``stagedS``), so the overlap with the previous step's
        device lane is visible rather than inferred.
        """
        records = self.recent(n, model=model)[::-1]  # chronological
        pids = {m: i + 1 for i, m in
                enumerate(sorted({r["model"] for r in records}))}
        has_prefetch = {r["model"] for r in records
                        if r.get("h2dOverlapMs", 0) > 0}
        events: List[Dict[str, Any]] = []
        for m, pid in pids.items():
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": m}})
            lanes = [(0, "host"), (1, "device")]
            if m in has_prefetch:
                lanes.append((2, "prefetch"))
            for tid, lane in lanes:
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tid, "args": {"name": lane}})
        for r in records:
            pid = pids[r["model"]]
            ts = r["startS"] * 1e6
            for key, name in (("hostWaitMs", "host_wait"),
                              ("h2dMs", "h2d"),
                              ("dispatchMs", "dispatch"),
                              ("deviceWaitMs", "device_wait")):
                dur = r.get(key, 0.0) * 1e3
                if dur <= 0:
                    continue
                events.append({"name": name, "ph": "X", "pid": pid,
                               "tid": 0, "ts": round(ts, 3),
                               "dur": round(dur, 3),
                               "args": {"step": r["step"]}})
                ts += dur
            if r["deviceStepMs"] > 0:
                dev_ts = r.get("dispatchS", r["startS"]) * 1e6
                events.append({"name": "device_step", "ph": "X", "pid": pid,
                               "tid": 1, "ts": round(dev_ts, 3),
                               "dur": round(r["deviceStepMs"] * 1e3, 3),
                               "args": {"step": r["step"],
                                        "examples": r["examples"]}})
            overlap = r.get("h2dOverlapMs", 0.0)
            if overlap > 0:
                dur = overlap * 1e3
                end = r.get("stagedS")
                if end is None:  # staging ended when the queue get returned
                    end = r["startS"] + r["hostWaitMs"] / 1e3
                events.append({"name": "h2d_overlap", "ph": "X", "pid": pid,
                               "tid": 2, "ts": round(end * 1e6 - dur, 3),
                               "dur": round(dur, 3),
                               "args": {"step": r["step"]}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0


_timeline = StepTimeline()
_timeline_lock = threading.Lock()


def get_timeline() -> StepTimeline:
    """THE process step-timeline ring (probe writes, servers serve)."""
    return _timeline


def set_timeline(timeline: StepTimeline) -> StepTimeline:
    """Swap the process timeline (tests); returns the previous one."""
    global _timeline
    with _timeline_lock:
        prev, _timeline = _timeline, timeline
    return prev


# -- process wiring ----------------------------------------------------------

def start_runtime_introspection(*, sample: bool = True) -> None:
    """Idempotent per-process bring-up, called by the servers: register
    the compile/memory instruments (so ``/metrics`` exposes the names
    before the first event) and start the memory-sampler thread."""
    get_compile_tracker().touch()
    sampler = get_memory_sampler()
    sampler.touch()
    sampler.start()
    if sample:
        try:
            sampler.sample_once()
        except Exception:
            logger.debug("initial device-memory sample failed",
                         exc_info=True)


def reset_runtime() -> None:
    """Test isolation: empty timeline + fresh peak window."""
    get_timeline().clear()
    get_memory_sampler().reset_peak()
