"""Training-pipeline probe: host-wait vs H2D vs device-step attribution.

BENCH_r05 measured a 45.9% (two-tower) and 87.0% (DLRM) gap between raw
feeder throughput and realized training examples/sec with no way to say
which side of the pipeline stalls.  This probe decomposes every training
iteration's wall time into named, separately-plotted components:

- ``host_wait``  — time blocked fetching the next batch (feeder / numpy)
- ``h2d``        — time converting + transferring the batch to device
- ``dispatch``   — time inside the step call itself: trace-cache lookup +
  argument handling + enqueue.  On backends that dispatch donated
  programs synchronously (CPU) the execution itself lands here — which
  is exactly why the component exists: without it the step wall hides
  between probe points and device_wait under-reports (it did, until
  ISSUE 7)
- ``device_wait``— time the HOST then stalls on the previous dispatched
  step (the device-bound residual)
- ``device_step``— dispatch→ready duration of each step (the device-step
  histogram proper)

The device measurements use a one-step lag so the probe never reduces
host/device overlap: after batch N+1 is staged, the loop must wait for
step N's output anyway (it is the next step's input), so blocking there
and timing the block attributes exactly the stall the pipeline already
pays.  wall ≈ host_wait + h2d + device_wait + loop overhead, which is the
decomposition ISSUE/BENCH needed.

With the PR-5 prefetched input pipeline (``data/prefetch.py``), batch
staging runs on a background thread and the transfer overlaps device
compute, so billing it to the step loop would be wrong twice over:
:meth:`PipelineProbe.iter_prefetched` times only the queue wait as
``host_wait`` and attributes the staging cost to the **overlap window**
(``pio_train_h2d_overlap_ms`` + the timeline's ``h2dOverlapMs``) instead
of the sync point.  The serialized ``h2d`` component of such steps is 0
by construction; ``tools/attribute_gap.py`` keeps reading the same
host-lane wall decomposition either way.

jax is imported lazily inside the sync so this module (like all of obs)
stays importable without an accelerator stack.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Iterable, Iterator, Optional

from predictionio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_MS,
    MetricsRegistry,
    get_registry,
)
from predictionio_tpu.obs.runtime import StepTimeline, get_timeline

__all__ = ["PipelineProbe"]


class _Timed:
    """Context manager recording elapsed ms into a histogram (+gauge) and
    the probe's current-iteration scratch (for the timeline record)."""

    __slots__ = ("_hist", "_gauge", "_labels", "_t0", "_cur", "_key")

    def __init__(self, hist, gauge, labels, cur=None, key=None):
        self._hist = hist
        self._gauge = gauge
        self._labels = labels
        self._cur = cur
        self._key = key
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        ms = (time.perf_counter() - self._t0) * 1e3
        self._hist.observe(ms, **self._labels)
        self._gauge.set(ms, **self._labels)
        if self._cur is not None:
            self._cur[self._key] = ms
        return False


def _sync_target(outputs: Any) -> Any:
    """Normalize dispatched outputs for ``jax.block_until_ready``.

    The model States (TwoTowerState/DLRMState) are plain dataclasses —
    deliberately NOT pytrees — so passed raw they are opaque leaves and
    ``block_until_ready`` silently skips their arrays, zeroing out the
    device_wait attribution.  Walking dataclass fields (and containers)
    down to real arrays makes the sync block on what the dispatch
    actually produced."""
    if dataclasses.is_dataclass(outputs) and not isinstance(outputs, type):
        return [_sync_target(getattr(outputs, f.name))
                for f in dataclasses.fields(outputs)]
    if isinstance(outputs, (list, tuple)):
        return [_sync_target(x) for x in outputs]
    return outputs


class PipelineProbe:
    """Per-model training-loop instrumentation over the shared registry.

    Inline integration shape (pre-prefetch; bench harnesses, custom loops)::

        probe = PipelineProbe("dlrm")
        for batch in probe.iter_host(epochs()):      # host_wait
            with probe.h2d():                        # h2d
                args = stage(batch)
            probe.sync()                             # device_wait (step N-1)
            state, loss = train_step(state, *args)
            probe.dispatched(state, examples=len(batch))
        probe.finish()                               # drain the last step

    Prefetched shape (two_tower.train / dlrm.train via DevicePrefetcher)::

        for batch in probe.iter_prefetched(pf):      # host_wait = queue wait
            probe.sync()                             # device_wait (step N-1)
            state, loss = train_step(state, *batch.args)
            probe.dispatched(state, examples=batch.examples)
        probe.finish()
    """

    def __init__(self, model: str,
                 registry: Optional[MetricsRegistry] = None,
                 timeline: Optional[StepTimeline] = None):
        reg = registry or get_registry()
        self.model = model
        self._timeline = timeline if timeline is not None else get_timeline()
        self._labels = {"model": model}
        self._host_wait = reg.histogram(
            "pio_train_host_wait_ms",
            "Time blocked fetching the next training batch (host side).",
            ("model",))
        self._h2d = reg.histogram(
            "pio_train_h2d_ms",
            "Time staging a batch for the device (convert + transfer).",
            ("model",))
        self._h2d_overlap = reg.histogram(
            "pio_train_h2d_overlap_ms",
            "Background staging time overlapped under device compute "
            "(prefetched pipeline; not part of the step-loop wall).",
            ("model",))
        self._dispatch = reg.histogram(
            "pio_train_dispatch_ms",
            "Time inside the step call (cache lookup + enqueue; on "
            "synchronous-dispatch backends the execution itself).",
            ("model",))
        self._device_wait = reg.histogram(
            "pio_train_device_wait_ms",
            "Host stall waiting on the previously dispatched device step.",
            ("model",))
        self._device_step = reg.histogram(
            "pio_train_device_step_ms",
            "Device-step duration: dispatch to outputs ready.",
            ("model",))
        self._last = {
            "host_wait": reg.gauge(
                "pio_train_last_host_wait_ms",
                "host_wait of the most recent iteration.", ("model",)),
            "h2d": reg.gauge(
                "pio_train_last_h2d_ms",
                "h2d of the most recent iteration.", ("model",)),
            "device_wait": reg.gauge(
                "pio_train_last_device_wait_ms",
                "device_wait of the most recent iteration.", ("model",)),
        }
        self._steps = reg.counter(
            "pio_train_steps_total", "Optimizer steps run.", ("model",))
        self._examples = reg.counter(
            "pio_train_examples_total",
            "Training examples consumed (pre-padding).", ("model",))
        self._pending: Optional[Any] = None
        self._pending_t0 = 0.0
        # Reference point for the dispatch interval: end of the last
        # sync (or of the batch fetch when nothing was in flight) up to
        # dispatched() — the step call's own wall.
        self._dispatch_ref: Optional[float] = None
        # Current-iteration scratch + the dispatched-step snapshot: the
        # loop overwrites _cur with step N's host_wait/h2d while step N-1
        # is still in flight, so dispatched() freezes _cur into
        # _pending_meta and sync() emits the completed step's timeline
        # record from the frozen copy.
        self._cur: dict = {}
        self._pending_meta: Optional[dict] = None
        self._step_no = 0

    # -- host side ---------------------------------------------------------

    def _iter_timed(self, it: Iterable, on_batch=None) -> Iterator:
        """Shared skeleton: each ``next()`` is timed as host_wait; the
        optional ``on_batch`` hook layers extra bookkeeping onto the
        fresh ``_cur`` scratch before the batch is yielded."""
        it = iter(it)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            ms = (time.perf_counter() - t0) * 1e3
            self._host_wait.observe(ms, **self._labels)
            self._last["host_wait"].set(ms, **self._labels)
            self._cur = {"host_wait": ms, "start_s": time.time() - ms / 1e3}
            self._dispatch_ref = time.perf_counter()
            if on_batch is not None:
                on_batch(batch)
            yield batch

    def iter_host(self, it: Iterable) -> Iterator:
        """Wrap a batch iterator; each ``next()`` is timed as host_wait."""
        return self._iter_timed(it)

    def h2d(self) -> _Timed:
        return _Timed(self._h2d, self._last["h2d"], self._labels,
                      self._cur, "h2d")

    def iter_prefetched(self, prefetcher: Iterable) -> Iterator:
        """Wrap a :class:`~predictionio_tpu.data.prefetch.DevicePrefetcher`
        stream: the queue wait is ``host_wait`` (the only serialized host
        cost left) and each batch's background staging time lands in the
        overlap window (``h2d_overlap``), NOT in the step-loop wall."""
        def on_batch(batch):
            overlap_ms = float(getattr(batch, "h2d_ms", 0.0))
            self._h2d_overlap.observe(overlap_ms, **self._labels)
            self._cur["h2d_overlap"] = overlap_ms
            self._cur["staged_s"] = getattr(batch, "staged_s", None)

        return self._iter_timed(prefetcher, on_batch)

    # -- device side (one-step lag) ----------------------------------------

    def sync(self) -> None:
        """Block on the previous step's outputs; the block time is the
        device-attributable stall, the dispatch→ready time is the step."""
        if self._pending is None:
            return
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(self._pending)
        t1 = time.perf_counter()
        self._dispatch_ref = t1
        self._device_wait.observe((t1 - t0) * 1e3, **self._labels)
        self._last["device_wait"].set((t1 - t0) * 1e3, **self._labels)
        self._device_step.observe((t1 - self._pending_t0) * 1e3,
                                  **self._labels)
        meta = self._pending_meta or {}
        self._timeline.record(
            self.model,
            step=meta.get("step"),
            start_s=meta.get("start_s"),
            host_wait_ms=meta.get("host_wait", 0.0),
            h2d_ms=meta.get("h2d", 0.0),
            h2d_overlap_ms=meta.get("h2d_overlap", 0.0),
            staged_s=meta.get("staged_s"),
            dispatch_s=meta.get("dispatch_s"),
            dispatch_ms=meta.get("dispatch", 0.0),
            device_wait_ms=(t1 - t0) * 1e3,
            device_step_ms=(t1 - self._pending_t0) * 1e3,
            examples=meta.get("examples", 0),
            fused_steps=meta.get("steps", 1))
        self._pending = None
        self._pending_meta = None

    def dispatched(self, outputs: Any, examples: int = 0,
                   steps: int = 1) -> None:
        """Register a freshly dispatched step's outputs for the next sync.

        ``steps`` is the optimizer-step count this ONE dispatch covers (a
        K-fused ``lax.scan`` window passes K): the steps counter advances
        by it, and the timeline record carries it so the per-dispatch
        wall is attributable to K steps downstream (attribute_gap)."""
        self._pending = _sync_target(outputs)
        self._pending_t0 = time.perf_counter()
        if self._dispatch_ref is not None:
            # The step call's own wall: everything between the last
            # probe point (sync, or batch fetch) and here.
            ms = (self._pending_t0 - self._dispatch_ref) * 1e3
            self._dispatch.observe(ms, **self._labels)
            self._cur["dispatch"] = ms
            self._dispatch_ref = None
        steps = max(int(steps), 1)
        self._steps.inc(steps, **self._labels)
        if examples:
            self._examples.inc(examples, **self._labels)
        self._step_no += steps
        meta = dict(self._cur)
        meta.setdefault("start_s", time.time())
        # True dispatch wall time: the Chrome-trace export starts the
        # device lane here instead of approximating from the step start,
        # so h2d/compute overlap renders exactly.
        meta["dispatch_s"] = time.time()
        meta["step"] = self._step_no
        meta["examples"] = examples
        meta["steps"] = steps
        self._pending_meta = meta
        self._cur = {}

    def finish(self) -> None:
        """Drain the last in-flight step (end of the training loop)."""
        self.sync()
