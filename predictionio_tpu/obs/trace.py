"""Request tracing: span trees, trace ids, JSONL export, slow-call logs.

Usage shape (the tentpole's API)::

    with trace("http.request", trace_id=req_id, server="engine") as t:
        with span("predict.algorithm", algo=name):
            ...

- :func:`trace` opens a ROOT span and binds a trace id for the current
  context (``contextvars``, so concurrent request-handler threads and
  asyncio tasks never share state).  On exit the finished span tree is
  handed to the process :class:`TraceRecorder`.
- :func:`span` opens a child of the innermost open span.  Outside any
  trace it still times the block but records nothing — instrumented
  library code (feeder, device_prep, serving internals) costs two
  ``perf_counter`` calls when tracing is not active.
- Trace ids are accepted/propagated over HTTP via ``X-Request-ID``
  (server/http.py); ids are sanitized here so a hostile header cannot
  smuggle newlines into the JSONL export or response headers.

Recorder outputs, all optional and all process-wide:

- in-memory ring buffer of the last N finished traces (``GET
  /traces.json`` on every server; N from ``PIO_TRACE_RING``, default 256)
- JSONL append to ``PIO_TRACE_FILE`` (one trace per line, self-contained)
- a WARNING log for any trace slower than its ``slow_ms`` threshold (the
  HTTP frontends pass ``PIO_SLOW_REQUEST_MS``, default 1000; 0 disables)
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "Span",
    "span",
    "trace",
    "attach_event",
    "current_span",
    "current_trace_id",
    "new_trace_id",
    "sanitize_trace_id",
    "TraceRecorder",
    "get_recorder",
    "set_recorder",
]

# Innermost open span for this context (None = tracing inactive).
_current_span: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("pio_current_span", default=None)
_current_trace_id: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("pio_current_trace_id", default=None)

_TRACE_ID_RE = re.compile(r"[^A-Za-z0-9._:-]")
_TRACE_ID_MAX = 128


def new_trace_id() -> str:
    return uuid.uuid4().hex


def sanitize_trace_id(raw: Optional[str]) -> Optional[str]:
    """Clamp a client-supplied X-Request-ID to a safe charset/length;
    None/empty (or fully-invalid) ids mean "generate one"."""
    if not raw:
        return None
    cleaned = _TRACE_ID_RE.sub("", str(raw))[:_TRACE_ID_MAX]
    return cleaned or None


def current_trace_id() -> Optional[str]:
    return _current_trace_id.get()


def current_span() -> Optional[Span]:
    """The innermost OPEN span of this context (None = tracing inactive).
    Lets out-of-band instrumentation (obs.runtime.publish_event) attach
    annotations to the request/run that triggered them."""
    return _current_span.get()


# Map perf_counter readings to wall clock ONCE: spans then pay a single
# perf_counter call at open instead of an extra time.time() each — the
# span tree sits on ~ms-scale request hot paths and must cost µs.
_EPOCH_WALL = time.time() - time.perf_counter()


class Span:
    """One timed node of a trace tree (name, attrs, children)."""

    __slots__ = ("name", "attrs", "children", "_t0", "duration_ms")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.attrs: Dict[str, Any] = attrs if attrs is not None else {}
        self.children: List[Span] = []
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None

    @property
    def start_s(self) -> float:
        return _EPOCH_WALL + self._t0

    def finish(self) -> None:
        if self.duration_ms is None:
            self.duration_ms = (time.perf_counter() - self._t0) * 1e3

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def children_ms(self) -> float:
        return sum(c.duration_ms or 0.0 for c in self.children)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "startS": round(self.start_s, 6),
            "durationMs": round(self.duration_ms or 0.0, 4),
        }
        if self.attrs:
            d["attrs"] = {k: _jsonable(v) for k, v in self.attrs.items()}
        if self.children:
            d["spans"] = [c.to_dict() for c in self.children]
        return d


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        # Structured attrs (the waterfall's stages map) keep their shape
        # in the recorded trace instead of collapsing to repr strings.
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


class span:
    """Child span of the innermost open span; no-op-cheap outside a trace.

    A hand-rolled context manager (not ``contextlib``): the generator
    protocol costs several µs per use, and seven spans ride every served
    query.  Detached use (no open trace) still times the block — callers
    may read ``.duration_ms`` — but records nothing.
    """

    __slots__ = ("_name", "_attrs", "_span", "_token")

    def __init__(self, name: str, **attrs):
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> Span:
        parent = _current_span.get()
        s = self._span = Span(self._name, self._attrs)
        if parent is None:
            self._token = None
        else:
            parent.children.append(s)
            self._token = _current_span.set(s)
        return s

    def __exit__(self, *exc) -> bool:
        self._span.finish()
        if self._token is not None:
            _current_span.reset(self._token)
        return False


def attach_event(parent: Optional[Span], name: str, **attrs) -> Span:
    """Zero-duration annotation on an EXPLICIT parent span.

    ``obs.runtime.publish_event`` attaches to the caller's contextvar
    span — useless for cross-thread producers like the serving
    micro-batcher, which annotates REQUEST spans from its own dispatcher
    thread.  The caller guarantees the parent's owning thread is parked
    (the request handler blocks on its pending result while the batcher
    writes), so the child append needs no lock.  ``parent=None`` records
    a standalone single-span trace instead, so the evidence is never
    silently dropped.
    """
    ev = Span(name, attrs)
    ev.duration_ms = 0.0
    if parent is not None:
        parent.children.append(ev)
        return ev
    get_recorder().record(new_trace_id(), ev)
    return ev


@contextlib.contextmanager
def trace(name: str, trace_id: Optional[str] = None,
          slow_ms: Optional[float] = None, recorder: Optional["TraceRecorder"] = None,
          **attrs):
    """Root span + trace id binding; records the finished tree on exit.

    Nested ``trace()`` calls degrade to plain child spans of the enclosing
    trace (one tree per request/run, never silently dropped timing).
    """
    if _current_span.get() is not None:
        with span(name, **attrs) as s:
            yield s
        return
    tid = sanitize_trace_id(trace_id) or new_trace_id()
    root = Span(name, attrs)
    tok_span = _current_span.set(root)
    tok_tid = _current_trace_id.set(tid)
    try:
        yield root
    finally:
        root.finish()
        _current_span.reset(tok_span)
        _current_trace_id.reset(tok_tid)
        (recorder or get_recorder()).record(tid, root, slow_ms=slow_ms)


class TraceRecorder:
    """Ring buffer + JSONL sink + slow-trace logging for finished traces."""

    def __init__(self, ring_size: Optional[int] = None):
        if ring_size is None:
            try:
                ring_size = int(os.environ.get("PIO_TRACE_RING", "256"))
            except ValueError:
                ring_size = 256
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(ring_size, 1))
        self._file_lock = threading.Lock()

    def record(self, trace_id: str, root: Span,
               slow_ms: Optional[float] = None) -> None:
        doc = {"traceId": trace_id, **root.to_dict()}
        with self._lock:
            self._ring.append(doc)
        path = os.environ.get("PIO_TRACE_FILE")
        if path:
            line = json.dumps(doc, separators=(",", ":"))
            try:
                # One atomic-ish append per trace; the file handle is not
                # cached so PIO_TRACE_FILE may change (or rotate) live.
                with self._file_lock, open(path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                logger.exception("cannot append trace to %s", path)
        dur = root.duration_ms or 0.0
        if slow_ms is not None and slow_ms > 0 and dur >= slow_ms:
            logger.warning(
                "slow %s: %.1f ms (threshold %.0f ms) trace=%s attrs=%s",
                root.name, dur, slow_ms, trace_id, root.attrs)

    def recent(self, n: int = 50, *, request_id: Optional[str] = None,
               min_ms: Optional[float] = None) -> List[Dict[str, Any]]:
        """Last ``n`` finished traces, most recent first (/traces.json).

        ``request_id`` filters to exact trace-id matches — the resolver
        for exemplar links out of the ``pio_serve_stage_ms`` waterfall
        buckets (ISSUE 9 satellite: an exemplar names ONE request; the
        endpoint must answer with that one trace, not the whole ring).
        ``min_ms`` keeps only traces at least that slow."""
        with self._lock:
            items = list(self._ring)
        out = items[::-1]
        if request_id is not None:
            out = [t for t in out if t.get("traceId") == request_id]
        if min_ms is not None:
            out = [t for t in out if (t.get("durationMs") or 0.0) >= min_ms]
        return out[:max(n, 0)]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_recorder = TraceRecorder()
_recorder_lock = threading.Lock()


def get_recorder() -> TraceRecorder:
    return _recorder


def set_recorder(recorder: TraceRecorder) -> TraceRecorder:
    global _recorder
    with _recorder_lock:
        prev, _recorder = _recorder, recorder
    return prev


def slow_request_ms() -> float:
    """The HTTP frontends' slow-request threshold (``PIO_SLOW_REQUEST_MS``,
    default 1000 ms; 0 or negative disables the WARNING log)."""
    try:
        return float(os.environ.get("PIO_SLOW_REQUEST_MS", "1000"))
    except ValueError:
        return 1000.0
