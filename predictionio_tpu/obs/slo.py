"""Serving SLO engine: multi-window burn rates wired to ``/ready``.

ISSUE 9 tentpole part 2, closing the ROADMAP rung "a saturation signal
from the autotuner ... wired to /ready so an LB can rotate a drowning
instance out instead of queueing into 429s".

Two objectives over the engine server's existing instruments:

- **availability** — fraction of ``/queries.json`` requests that
  succeeded (``pio_query_requests_total`` vs ``pio_query_errors_total``;
  the error counter deliberately includes 429/503/504 — under overload
  those ARE the user-visible failures an LB should react to).
- **latency** — fraction of requests answering within the target
  (``pio_query_latency_ms`` mass at/below ``latency_target_ms``, which
  defaults from ``PIO_BATCH_P99_TARGET_MS`` so the SLO and the batch
  autotuner chase the same number).

Burn rate = (bad fraction over a window) / (error budget).  Burn 1.0
spends the budget exactly at period end; the classic multi-window rule
trips only when BOTH a fast (~5m) and a slow (~1h) window burn hot — the
fast window proves it's still happening, the slow one that it's
sustained, so a single latency spike never flips readiness.

The degradation signal COMBINES burn with the serving autotuner's
persistent-floor saturation detector (``WindowAutotuner.saturated()``:
the controller pinned its window at the floor and keeps saying "floor" —
offered load exceeds capacity):

- sustained burn over both windows  → degraded (whatever the cause);
- saturation alone, SLO still met   → stay ready (the batcher is coping);
- saturation + fast window burning  → degraded immediately, without
  waiting for the slow window (the saturation detector supplies the
  "it's sustained" evidence the slow window otherwise provides).

Hysteresis is asymmetric: trip immediately, clear only after the trip
condition has been false for ``recovery_s`` on the SAME clock — a
drowning instance that sheds its queue the moment the LB rotates it out
must not flap straight back in.  ``PIO_READY_SLO=off`` is the operator
escape hatch: burn gauges keep exporting, ``/ready`` stops acting on
them.

Everything rides an injectable monotonic clock (tests drive hours of
burn in microseconds), and ticks are pulled lazily by ``/ready`` /
``/stats.json`` polls — no extra timer thread.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

import time

from predictionio_tpu.config import env_bool
from predictionio_tpu.obs.metrics import get_registry

__all__ = ["SLOConfig", "SLOEngine"]


def _env_f(env, key: str, default: float) -> float:
    raw = env.get(key)
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class SLOConfig:
    """Objectives + burn policy; :meth:`from_env` is the production
    constructor (knobs documented in README's table)."""

    availability_objective: float = 0.999
    latency_objective: float = 0.99
    latency_target_ms: float = 100.0
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 14.4      # Google SRE fast-burn page point
    saturation_burn_min: float = 1.0  # fast burn needed WITH saturation
    min_requests: int = 10            # fast-window floor against flapping
    recovery_s: float = 60.0          # trip-condition-false dwell to clear
    ready_slo: bool = True            # PIO_READY_SLO escape hatch

    @classmethod
    def from_env(cls, env=None) -> "SLOConfig":
        env = os.environ if env is None else env
        return cls(
            availability_objective=min(max(_env_f(
                env, "PIO_SLO_AVAILABILITY", 0.999), 0.0), 0.999999),
            latency_objective=min(max(_env_f(
                env, "PIO_SLO_LATENCY_OBJECTIVE", 0.99), 0.0), 0.999999),
            latency_target_ms=_env_f(
                env, "PIO_SLO_LATENCY_TARGET_MS",
                _env_f(env, "PIO_BATCH_P99_TARGET_MS", 100.0)),
            fast_window_s=_env_f(env, "PIO_SLO_FAST_WINDOW_S", 300.0),
            slow_window_s=_env_f(env, "PIO_SLO_SLOW_WINDOW_S", 3600.0),
            burn_threshold=_env_f(env, "PIO_SLO_BURN_THRESHOLD", 14.4),
            min_requests=int(_env_f(env, "PIO_SLO_MIN_REQUESTS", 10)),
            recovery_s=_env_f(env, "PIO_SLO_RECOVERY_S", 60.0),
            ready_slo=env_bool(env.get("PIO_READY_SLO"), True),
        )


class _Snapshot:
    __slots__ = ("t", "total", "errors", "lat_total", "lat_good")

    def __init__(self, t, total, errors, lat_total, lat_good):
        self.t = t
        self.total = total
        self.errors = errors
        self.lat_total = lat_total
        self.lat_good = lat_good


class SLOEngine:
    """Windowed burn rates over the process registry + the readiness
    verdict.  ``saturation_fn`` is the autotuner's persistent-floor
    detector (None = never saturated)."""

    # Pull-driven tick coalescing: an LB polling /ready at 1 Hz must not
    # grow the snapshot ring once per poll.
    MIN_TICK_INTERVAL_S = 1.0

    def __init__(self, config: Optional[SLOConfig] = None, *,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 saturation_fn: Optional[Callable[[], bool]] = None):
        self.config = config or SLOConfig.from_env()
        self.registry = registry or get_registry()
        self.clock = clock
        self.saturation_fn = saturation_fn
        self._lock = threading.Lock()
        self._snaps: Deque[_Snapshot] = deque()
        self._last_tick: Optional[float] = None
        self._degraded = False
        self._degraded_since: Optional[float] = None
        self._clear_since: Optional[float] = None  # trip-false dwell start
        self._last: Dict[str, Any] = {}
        reg = self.registry
        self._g_burn = reg.gauge(
            "pio_slo_burn_rate",
            "Error-budget burn rate by objective and window "
            "(1.0 = budget spent exactly at period end).",
            ("slo", "window"))
        self._g_objective = reg.gauge(
            "pio_slo_objective", "Configured SLO objective.", ("slo",))
        self._g_target = reg.gauge(
            "pio_slo_latency_target_ms",
            "Latency SLO threshold (defaults from PIO_BATCH_P99_TARGET_MS).")
        self._g_degraded = reg.gauge(
            "pio_slo_degraded",
            "1 while the SLO/saturation signal holds /ready at 503.")
        self._g_saturated = reg.gauge(
            "pio_slo_saturated",
            "1 while the serving autotuner reports persistent-floor "
            "saturation (offered load > capacity).")
        self._g_objective.set(self.config.availability_objective,
                              slo="availability")
        self._g_objective.set(self.config.latency_objective, slo="latency")
        self._g_target.set(self.config.latency_target_ms)

    # -- sampling -----------------------------------------------------------

    def _sample(self, now: float) -> _Snapshot:
        reg = self.registry
        total = errors = lat_total = lat_good = 0.0
        c = reg.get("pio_query_requests_total")
        if c is not None:
            total = c.total()
        c = reg.get("pio_query_errors_total")
        if c is not None:
            errors = c.total()
        h = reg.get("pio_query_latency_ms")
        if h is not None:
            lat_total = float(h.count())
            lat_good = h.count_le(self.config.latency_target_ms)
        return _Snapshot(now, total, errors, lat_total, lat_good)

    def _window_burn(self, now: float,
                     window_s: float) -> Tuple[float, float, float]:
        """(availability_burn, latency_burn, requests) over the trailing
        window.  Caller holds the lock; the newest snapshot is current."""
        newest = self._snaps[-1]
        oldest = self._snaps[0]
        for s in self._snaps:
            if s.t >= now - window_s:
                break
            oldest = s
        d_total = max(newest.total - oldest.total, 0.0)
        d_err = max(newest.errors - oldest.errors, 0.0)
        d_lat = max(newest.lat_total - oldest.lat_total, 0.0)
        d_good = max(newest.lat_good - oldest.lat_good, 0.0)
        avail_bad = (d_err / d_total) if d_total else 0.0
        lat_bad = (max(d_lat - d_good, 0.0) / d_lat) if d_lat else 0.0
        avail_burn = avail_bad / max(
            1.0 - self.config.availability_objective, 1e-9)
        lat_burn = lat_bad / max(
            1.0 - self.config.latency_objective, 1e-9)
        return avail_burn, lat_burn, d_total

    # -- the engine ---------------------------------------------------------

    def tick(self, force: bool = False) -> Dict[str, Any]:
        """Sample, recompute burn/degradation, publish gauges.  Pulled by
        ``/ready`` and the stats views; coalesced to one real tick per
        :data:`MIN_TICK_INTERVAL_S` unless ``force``."""
        now = self.clock()
        with self._lock:
            if (not force and self._last_tick is not None
                    and now - self._last_tick < self.MIN_TICK_INTERVAL_S
                    and self._last):
                return dict(self._last)
            self._last_tick = now
            self._snaps.append(self._sample(now))
            horizon = now - self.config.slow_window_s - 60.0
            while len(self._snaps) > 2 and self._snaps[1].t <= horizon:
                self._snaps.popleft()
            fast_a, fast_l, fast_n = self._window_burn(
                now, self.config.fast_window_s)
            slow_a, slow_l, _ = self._window_burn(
                now, self.config.slow_window_s)
            fast = max(fast_a, fast_l)
            slow = max(slow_a, slow_l)
            saturated = bool(self.saturation_fn()) \
                if self.saturation_fn else False
            thr = self.config.burn_threshold
            enough = fast_n >= self.config.min_requests
            sustained_burn = enough and fast >= thr and slow >= thr
            saturated_burn = (saturated and enough
                              and fast >= self.config.saturation_burn_min)
            trip = sustained_burn or saturated_burn
            if trip:
                if not self._degraded:
                    self._degraded = True
                    self._degraded_since = now
                self._clear_since = None
            elif self._degraded:
                # Hysteresis: the trip condition must stay false for
                # recovery_s before readiness returns.
                if self._clear_since is None:
                    self._clear_since = now
                elif now - self._clear_since >= self.config.recovery_s:
                    self._degraded = False
                    self._degraded_since = None
                    self._clear_since = None
            reasons = []
            if sustained_burn:
                reasons.append("sustained_burn")
            if saturated_burn:
                reasons.append("saturation_with_burn")
            state = {
                "readySlo": self.config.ready_slo,
                "degraded": self._degraded,
                "degradedSinceS": (round(now - self._degraded_since, 1)
                                   if self._degraded_since is not None
                                   else None),
                "recoveringForS": (round(now - self._clear_since, 1)
                                   if self._clear_since is not None
                                   else None),
                "tripReasons": reasons,
                "saturated": saturated,
                "burn": {
                    "fast": {"availability": round(fast_a, 3),
                             "latency": round(fast_l, 3),
                             "requests": int(fast_n)},
                    "slow": {"availability": round(slow_a, 3),
                             "latency": round(slow_l, 3)},
                },
                "threshold": thr,
                "objectives": {
                    "availability": self.config.availability_objective,
                    "latency": self.config.latency_objective,
                    "latencyTargetMs": self.config.latency_target_ms,
                },
            }
            self._last = state
        self._g_burn.set(fast_a, slo="availability", window="fast")
        self._g_burn.set(fast_l, slo="latency", window="fast")
        self._g_burn.set(slow_a, slo="availability", window="slow")
        self._g_burn.set(slow_l, slo="latency", window="slow")
        self._g_degraded.set(1 if state["degraded"] else 0)
        self._g_saturated.set(1 if saturated else 0)
        return dict(state)

    def ready(self) -> Tuple[bool, Dict[str, Any]]:
        """The /ready verdict: (serving_ok, slo_state).  With
        ``PIO_READY_SLO=off`` the state still reports ``degraded`` but
        the verdict is always True."""
        state = self.tick()
        if not self.config.ready_slo:
            return True, state
        return not state["degraded"], state

    def snapshot(self) -> Dict[str, Any]:
        """Status-page / fleet view (same doc the last tick produced)."""
        return self.tick()
