"""On-demand JAX profiler capture (Dapper-style sample-on-demand).

Continuous xplane capture is too heavy to leave on, so capture is armed
on demand — ``POST /admin/profile?duration_ms=`` on the admin server or
the ``pio profile`` CLI verb — runs for a bounded window, and stops
itself.  One capture at a time per process (the underlying
``jax.profiler`` session is a process singleton).

The start/stop callables are injectable so tests exercise the whole
state machine — busy, finished, platform-can't-capture — with fakes and
no real profiler artifacts; the HTTP layer maps
:class:`ProfilerUnavailable` to a clear **501** instead of crashing when
the platform cannot capture (no jax, no profiler plugin, remote-tunnel
backends).
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from predictionio_tpu.obs.runtime import publish_event

logger = logging.getLogger(__name__)

__all__ = [
    "ProfilerUnavailable",
    "ProfilerBusy",
    "ProfilerSession",
    "get_profiler",
    "set_profiler",
    "capture",
]

# Hard ceiling on a requested capture window: an unattended multi-minute
# xplane capture can fill a disk.
MAX_CAPTURE_MS = 600_000.0


class ProfilerUnavailable(RuntimeError):
    """This platform/process cannot capture a profile (mapped to 501)."""


class ProfilerBusy(RuntimeError):
    """A capture is already running (mapped to 409)."""


def _default_start(path: str) -> None:
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is present in CI
        raise ProfilerUnavailable(f"jax unavailable: {e}") from e
    try:
        jax.profiler.start_trace(path)
    except ProfilerUnavailable:
        raise
    except Exception as e:
        raise ProfilerUnavailable(
            f"profiler capture unsupported here: {e}") from e


def _default_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfilerSession:
    """One-at-a-time timed profiler capture with injectable backend.

    ``start(duration_ms)`` arms the capture and schedules the stop on a
    timer thread; ``stop()`` is idempotent and safe to call early.  The
    artifact directory defaults to a fresh ``pio_profile_*`` temp dir
    (override per call or via ``PIO_PROFILE_OUT``).
    """

    def __init__(self,
                 start_fn: Callable[[str], None] = _default_start,
                 stop_fn: Callable[[], None] = _default_stop,
                 clock: Callable[[], float] = time.monotonic,
                 timer_factory: Callable[..., threading.Timer]
                 = threading.Timer):
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._clock = clock
        self._timer_factory = timer_factory
        self._lock = threading.Lock()
        # Serializes in-memory artifact tar builds (see artifact()).
        self._artifact_lock = threading.Lock()
        self._active_path: Optional[str] = None
        self._started_at: Optional[float] = None
        self._duration_ms: float = 0.0
        self._timer: Optional[threading.Timer] = None
        self._last_path: Optional[str] = None

    def start(self, duration_ms: float,
              out_dir: Optional[str] = None) -> Dict[str, Any]:
        """Arm a capture; returns {"path", "durationMs"}.

        Raises :class:`ProfilerBusy` when a capture is running and
        :class:`ProfilerUnavailable` when the platform cannot capture.
        """
        try:
            duration_ms = float(duration_ms)
        except (TypeError, ValueError):
            raise ValueError(f"bad duration_ms: {duration_ms!r}") from None
        if not duration_ms > 0:
            raise ValueError("duration_ms must be > 0")
        duration_ms = min(duration_ms, MAX_CAPTURE_MS)
        path = (out_dir or os.environ.get("PIO_PROFILE_OUT")
                or tempfile.mkdtemp(prefix="pio_profile_"))
        with self._lock:
            if self._active_path is not None:
                raise ProfilerBusy(
                    f"capture already running to {self._active_path}")
            self._start_fn(path)  # ProfilerUnavailable propagates un-armed
            self._active_path = path
            self._started_at = self._clock()
            self._duration_ms = duration_ms
            self._timer = self._timer_factory(duration_ms / 1e3, self.stop)
            self._timer.daemon = True
            self._timer.start()
        publish_event("profiler.start", path=path,
                      durationMs=round(duration_ms, 1))
        logger.info("profiler capture started: %s (%.0f ms)", path,
                    duration_ms)
        return {"path": path, "durationMs": duration_ms}

    def stop(self) -> Optional[str]:
        """Finish the active capture; returns its path (None if idle)."""
        with self._lock:
            path = self._active_path
            if path is None:
                return None
            timer, self._timer = self._timer, None
            self._active_path = None
            self._started_at = None
            self._last_path = path
            try:
                self._stop_fn()
            except Exception:
                # the capture window still produced whatever landed on
                # disk before the stop failed — report the path anyway
                logger.exception("profiler stop failed (artifacts may be "
                                 "partial): %s", path)
        if timer is not None:
            timer.cancel()
        publish_event("profiler.stop", path=path)
        logger.info("profiler capture finished: %s", path)
        return path

    def status(self) -> Dict[str, Any]:
        with self._lock:
            if self._active_path is None:
                return {"active": False, "lastPath": self._last_path}
            elapsed_ms = (self._clock() - (self._started_at or 0.0)) * 1e3
            return {"active": True, "path": self._active_path,
                    "durationMs": self._duration_ms,
                    "remainingMs": max(self._duration_ms - elapsed_ms, 0.0)}

    def artifact(self) -> Optional[Tuple[bytes, str]]:
        """(tar.gz bytes, filename) of the LAST finished capture — the
        download behind ``GET /admin/profile/artifact`` (ISSUE 9
        satellite: captures returned server-local paths since PR 3, so
        remote/fleet operation needed box access to retrieve them).

        Only the session's own ``_last_path`` is ever archived — the
        endpoint can not be steered at arbitrary server paths.  Returns
        None when no finished capture exists (HTTP 404 upstream); raises
        :class:`ProfilerBusy` while one is running (the artifact is
        still being written).

        The archive is built in memory (the handler plumbing answers
        with payload bytes either way); concurrent downloads serialize
        on a build lock so N clients cost ONE archive's peak at a time,
        not N."""
        import io
        import tarfile

        with self._artifact_lock:
            # Busy-check INSIDE the build lock: a waiter that queued
            # behind another download must re-validate, or a capture
            # armed meanwhile (same PIO_PROFILE_OUT dir) gets archived
            # while being written.
            with self._lock:
                if self._active_path is not None:
                    raise ProfilerBusy(
                        f"capture still running to {self._active_path}")
                path = self._last_path
            if not path or not os.path.isdir(path):
                return None
            buf = io.BytesIO()
            base = os.path.basename(os.path.normpath(path)) or "pio_profile"
            try:
                with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                    tar.add(path, arcname=base)
            except OSError as e:
                # Files vanished/changed mid-walk: a capture started into
                # this directory after the busy-check — same verdict as
                # catching it before (409), never a truncated archive.
                raise ProfilerBusy(
                    f"capture artifacts changed while archiving: {e}")
            return buf.getvalue(), f"{base}.tar.gz"


_profiler = ProfilerSession()
_profiler_lock = threading.Lock()


def get_profiler() -> ProfilerSession:
    """THE process profiler session (admin server + CLI)."""
    return _profiler


def set_profiler(session: ProfilerSession) -> ProfilerSession:
    """Swap the process session (tests); returns the previous one."""
    global _profiler
    with _profiler_lock:
        prev, _profiler = _profiler, session
    return prev


def capture(duration_ms: float, out_dir: Optional[str] = None,
            sleep: Callable[[float], None] = time.sleep) -> str:
    """Blocking capture (the local ``pio profile`` path): start, wait the
    window out, stop, return the artifact path."""
    session = get_profiler()
    info = session.start(duration_ms, out_dir)
    # start() caps the window at MAX_CAPTURE_MS — wait out the CAPPED
    # duration, not the raw request, or an over-asked CLI blocks long
    # after the timer already stopped the capture.
    sleep(info["durationMs"] / 1e3)
    return session.stop() or info["path"]
