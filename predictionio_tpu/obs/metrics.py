"""Process-wide metrics registry: Counter / Gauge / Histogram + renderer.

The rebuild's Prometheus-style metrics were called for by SURVEY §5.5 as a
first-class addition over upstream PredictionIO, but until this module each
server hand-rolled its own counters and ``/metrics`` text emitter and the
training side had none.  This is the single source of truth: servers,
workflows, the native feeder binding, and plugins all register instruments
here, and ``GET /metrics`` / ``GET /stats.json`` / ``pio status`` are thin
views over one registry.

Design constraints:

- stdlib only (obs must be importable before jax/numpy — the CLI's status
  path and the servers cannot afford a heavyweight dependency);
- thread-safe: instruments are hit from every request-handler thread and
  from the training loop concurrently (one lock per instrument, held only
  for the dict update — no I/O under lock);
- label support with Prometheus text-exposition escaping;
- instruments are get-or-create by name so independently constructed
  servers in one process share series instead of colliding.

Naming convention (enforced only by review, documented in README):
``pio_<server|subsystem>_<what>_<unit>`` — e.g. ``pio_event_requests_total``,
``pio_train_host_wait_ms``.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "set_registry",
    "DEFAULT_LATENCY_BUCKETS_MS",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Request/step latency buckets in milliseconds: sub-ms serving fast paths
# up through multi-minute training phases.
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000, 300000,
)


def _fmt_value(v: float) -> str:
    """Prometheus sample-value formatting: integral floats render bare
    (``1`` not ``1.0``) so counters read naturally; everything else uses
    repr (full precision round-trip)."""
    if v != v:  # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _label_pairs(labelnames: Sequence[str], labelvalues: Tuple[str, ...],
                 extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label_value(v)}"'
             for n, v in zip(labelnames, labelvalues)]
    pairs.extend(f'{n}="{_escape_label_value(v)}"' for n, v in extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_exemplar(ex: Optional[Tuple[str, float]]) -> str:
    """OpenMetrics exemplar suffix for a bucket line (empty when none).

    Only rendered on the opt-in ``?exemplars=1`` view — classic 0.0.4
    allows nothing but an optional timestamp after the value, so a
    strict Prometheus scraper would reject an exposition carrying these.
    Our own parsers (fleet aggregation, pio status, bench) strip the
    suffix explicitly either way."""
    if not ex:
        return ""
    trace_id, v = ex
    return (f' # {{trace_id="{_escape_label_value(trace_id)}"}}'
            f' {_fmt_value(v)}')


class _Metric:
    """Shared base: name/help/labelnames validation + per-series storage."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def render(self) -> List[str]:  # pragma: no cover - overridden
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter; ``inc`` with the instrument's exact label set."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._series: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]  # an unlabelled counter exists from t=0
        return [f"{self.name}{_label_pairs(self.labelnames, k)} "
                f"{_fmt_value(v)}" for k, v in items]


class Gauge(_Metric):
    """Set/inc/dec instantaneous value."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._series: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [f"{self.name}{_label_pairs(self.labelnames, k)} "
                f"{_fmt_value(v)}" for k, v in items]


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, value) of the LAST observation that
        # landed there with an exemplar attached (OpenMetrics-style).
        self.exemplars: Dict[int, Tuple[str, float]] = {}


class Histogram(_Metric):
    """Bucketed distribution with Prometheus cumulative-``le`` rendering
    and a quantile estimator for the JSON stats views."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        if bs and bs[-1] == math.inf:
            bs = bs[:-1]  # +Inf is implicit
        self.buckets = tuple(bs)
        self._series: Dict[Tuple[str, ...], _HistSeries] = {}

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation.  ``exemplar`` is an optional trace id
        stored per (series, bucket) and rendered OpenMetrics-style after
        the bucket line, linking the bucket to its ``/traces.json`` entry
        (ISSUE 9 waterfall: "why is THIS bucket populated?" answers with
        a concrete request to open)."""
        key = self._key(labels)
        v = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets) + 1)
            i = len(self.buckets)  # +Inf slot
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            s.counts[i] += 1
            s.sum += v
            s.count += 1
            if exemplar:
                s.exemplars[i] = (str(exemplar), v)

    def exemplars(self, **labels) -> Dict[float, Tuple[str, float]]:
        """{bucket_le: (trace_id, value)} for one series (+Inf = inf)."""
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return {}
            bounds = self.buckets + (math.inf,)
            return {bounds[i]: ex for i, ex in s.exemplars.items()}

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            return s.count if s else 0

    def sum(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            return s.sum if s else 0.0

    def count_le(self, value: float, **labels) -> float:
        """Estimated observations ≤ ``value`` (linear interpolation inside
        the containing bucket) — the latency-SLO "good events" reading.
        Conservative at bucket edges exactly like :meth:`quantile`."""
        key = self._key(labels)
        v = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None or s.count == 0:
                return 0.0
            counts = list(s.counts)
        cum = 0.0
        lo = 0.0
        for j, b in enumerate(self.buckets):
            if v <= b:
                if counts[j] and b > lo:
                    frac = (v - lo) / (b - lo)
                    cum += counts[j] * min(max(frac, 0.0), 1.0)
                return cum
            cum += counts[j]
            lo = b
        # Past the top finite bound: +Inf-bucket observations have no
        # upper bound, so they count as NOT ≤ value (under-counts goods —
        # the safe direction for an SLO).
        return cum

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate (the /stats.json view).

        Linear interpolation inside the bucket holding the q-th sample;
        values landing in the +Inf bucket report the top finite bound
        (an under-estimate, flagged by the bucket counts themselves).
        """
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None or s.count == 0:
                return 0.0
            counts = list(s.counts)
            total = s.count
        target = q * total
        cum = 0.0
        lo = 0.0
        for j, b in enumerate(self.buckets):
            prev_cum = cum
            cum += counts[j]
            if cum >= target and counts[j] > 0:
                frac = (target - prev_cum) / counts[j]
                return lo + (b - lo) * min(max(frac, 0.0), 1.0)
            lo = b
        return self.buckets[-1]

    def merged_quantile(self, q: float) -> float:
        """Quantile over ALL series of this histogram merged — the
        aggregate view used when labels only partition one logical
        stream (e.g. per-route request latency)."""
        with self._lock:
            merged = [0] * (len(self.buckets) + 1)
            total = 0
            for s in self._series.values():
                total += s.count
                for j, c in enumerate(s.counts):
                    merged[j] += c
        if total == 0:
            return 0.0
        target = q * total
        cum = 0.0
        lo = 0.0
        for j, b in enumerate(self.buckets):
            prev_cum = cum
            cum += merged[j]
            if cum >= target and merged[j] > 0:
                frac = (target - prev_cum) / merged[j]
                return lo + (b - lo) * min(max(frac, 0.0), 1.0)
            lo = b
        return self.buckets[-1]

    def render(self, exemplars: bool = False) -> List[str]:
        with self._lock:
            items = [(k, list(s.counts), s.sum, s.count,
                      dict(s.exemplars) if exemplars else {})
                     for k, s in sorted(self._series.items())]
        lines: List[str] = []
        for key, counts, ssum, scount, exs in items:
            cum = 0
            for j, (b, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_label_pairs(self.labelnames, key, (('le', _fmt_value(b)),))}"
                    f" {cum}{_fmt_exemplar(exs.get(j))}")
            lines.append(
                f"{self.name}_bucket"
                f"{_label_pairs(self.labelnames, key, (('le', '+Inf'),))}"
                f" {scount}{_fmt_exemplar(exs.get(len(self.buckets)))}")
            lines.append(f"{self.name}_sum"
                         f"{_label_pairs(self.labelnames, key)} "
                         f"{_fmt_value(ssum)}")
            lines.append(f"{self.name}_count"
                         f"{_label_pairs(self.labelnames, key)} {scount}")
        return lines


class MetricsRegistry:
    """Get-or-create instrument registry + the ONE text renderer.

    ``counter``/``gauge``/``histogram`` return the existing instrument when
    the name is already registered (validating kind and labelnames match),
    so a second server instance in the same process shares series rather
    than shadowing them.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} labelnames mismatch: "
                        f"{m.labelnames} vs {tuple(labelnames)}")
                want_buckets = kw.get("buckets")
                if want_buckets is not None:
                    norm = tuple(sorted(float(b) for b in want_buckets
                                        if b != math.inf))
                    if norm != m.buckets:
                        raise ValueError(
                            f"histogram {name!r} buckets mismatch: "
                            f"{m.buckets} vs {norm}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format 0.0.4 for the whole process.

        ``exemplars=True`` appends OpenMetrics-style exemplar suffixes
        to histogram bucket lines.  That syntax is NOT part of classic
        0.0.4 — a strict Prometheus scraper rejects the whole exposition
        over it — so the default render stays clean and the servers only
        opt in for ``/metrics?exemplars=1`` (our own tools: the trace
        resolver behind the waterfall buckets)."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render(exemplars=exemplars)
                         if isinstance(m, Histogram) else m.render())
        return "\n".join(lines) + "\n"

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Drop every instrument (test isolation; never in production)."""
        with self._lock:
            self._metrics.clear()


_registry = MetricsRegistry()
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """THE process-wide registry (servers, workflow, feeder, plugins)."""
    return _registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process registry (tests); returns the previous one."""
    global _registry
    with _registry_lock:
        prev, _registry = _registry, registry
    return prev
