"""Fleet-aggregated telemetry (ISSUE 9 tentpole part 3).

The dashboard scraped exactly one process; the ROADMAP's multi-instance
open item needs ``/metrics`` + ``/timeline.json`` + SLO state merged
across N engine/event servers before anything can scale horizontally
behind a load balancer.  This module is the telemetry half of that item:

- :func:`parse_exposition` — Prometheus text-format parser (tolerates
  the OpenMetrics exemplar suffix our histograms emit);
- :func:`merge_samples` — TYPE-correct merge: **counters sum**,
  **histogram buckets add** (per-``le`` addition is associative and
  sum-preserving by construction — the metrics lint keeps bucket schemas
  identical across instances), **gauges never merge** — each instance's
  reading survives under an added ``instance`` label (summing two
  ``pio_model_generation`` values is meaningless);
- :class:`CounterResetTracker` — an instance restart resets its
  cumulative series to 0; the tracker detects the drop and carries the
  pre-restart total as an offset so fleet sums never go backwards;
- :class:`FleetAggregator` — scrapes a configured instance list, merges,
  and serves the ``/fleet.json`` payload (dashboard) / the ``pio status
  --fleet`` summary.  A dead instance degrades to a **marked-stale
  entry** that keeps contributing its last-known counters (sums must not
  dip just because one scrape failed), never an exception.

Configuration: ``PIO_FLEET_INSTANCES`` — comma-separated base URLs
(``http://host:port``), or the dashboard's ``--fleet`` flag.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "parse_exposition",
    "merge_samples",
    "merge_histogram_buckets",
    "CounterResetTracker",
    "FleetAggregator",
    "fleet_instances_from_env",
]

# Cumulative-series suffixes a histogram family renders; they reset on
# restart exactly like counters, so the reset tracker covers them too.
_CUMULATIVE_SUFFIXES = ("_bucket", "_sum", "_count")

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?\s+(?P<value>[^\s#]+)')
# OpenMetrics exemplar suffix on a bucket line (` # {trace_id="..."} v`):
# stripped BEFORE sample matching — the greedy label regex would
# otherwise swallow it, taking the exemplar VALUE as the sample value
# and leaking trace_id in as a label.
_EXEMPLAR_SUFFIX_RE = re.compile(r'\s#\s\{.*\}\s+\S+(\s+\S+)?$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


# Single-pass unescape (the sequential .replace() order corrupts values
# holding an escaped backslash before an 'n': '\\\\n' must be
# backslash+'n', never backslash+newline).
_ESCAPE_RE = re.compile(r"\\(.)")
_ESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape(v: str) -> str:
    return _ESCAPE_RE.sub(
        lambda m: _ESCAPES.get(m.group(1), "\\" + m.group(1)), v)


def parse_exposition(text: str) -> Tuple[Dict[str, str], List[Tuple]]:
    """(types, samples) from Prometheus text exposition.

    ``types`` maps family name → kind; ``samples`` is a list of
    ``(name, labels_dict, value)``.  Exemplar suffixes (`` # {...}``)
    after the value are ignored; unparseable lines are skipped — a
    hostile/foreign exposition must not 500 the aggregator.
    """
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(_EXEMPLAR_SUFFIX_RE.sub("", line))
        if not m:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        raw = m.group("value")
        try:
            value = float(raw)
        except ValueError:
            continue
        samples.append((m.group("name"), labels, value))
    return types, samples


def _family(name: str, types: Dict[str, str]) -> Tuple[str, str]:
    """(family_name, kind) for a sample name, resolving the histogram
    child series (``*_bucket``/``*_sum``/``*_count``) to their family."""
    kind = types.get(name)
    if kind is not None:
        return name, kind
    for suffix in _CUMULATIVE_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base, "histogram"
    return name, "untyped"


def _series_key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def merge_histogram_buckets(parts: Iterable[Dict[str, float]]
                            ) -> Dict[str, float]:
    """Add per-``le`` cumulative bucket counts.  Plain addition over a
    shared ``le`` schema: associative, commutative, and sum-preserving
    (the fleet-merge correctness tests pin all three)."""
    out: Dict[str, float] = {}
    for p in parts:
        for le, c in p.items():
            out[le] = out.get(le, 0.0) + c
    return out


class CounterResetTracker:
    """Carries cumulative series across instance restarts.

    ``update(instance, series_key, raw)`` returns the restart-corrected
    effective value: when a scrape shows the raw value DROPPED, the
    instance restarted and its pre-restart total becomes an offset.
    State is per aggregator instance — two dashboards each converge on
    correct sums independently."""

    def __init__(self):
        self._state: Dict[Tuple[str, str], Tuple[float, float]] = {}

    def update(self, instance: str, series_key: str, raw: float) -> float:
        key = (instance, series_key)
        last_raw, offset = self._state.get(key, (0.0, 0.0))
        if raw < last_raw:
            offset += last_raw  # reset detected: bank the old total
        self._state[key] = (raw, offset)
        return raw + offset


def merge_samples(per_instance: Dict[str, Tuple[Dict[str, str], List[Tuple]]],
                  reset_tracker: Optional[CounterResetTracker] = None
                  ) -> Dict[str, Any]:
    """TYPE-correct merge of several instances' parsed expositions.

    ``per_instance``: instance → (types, samples).  Returns::

        {"counters":   {series_key: summed_value},
         "gauges":     {series_key_with_instance_label: value},
         "histograms": {family: {series_key(no le): {"buckets": {le: n},
                                                     "sum": s,
                                                     "count": n}}},
         "types":      {family: kind}}
    """
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Dict[str, Any]]] = {}
    all_types: Dict[str, str] = {}
    for instance, (types, samples) in sorted(per_instance.items()):
        all_types.update(types)
        for name, labels, value in samples:
            family, kind = _family(name, types)
            if kind == "counter":
                key = _series_key(name, labels)
                eff = (reset_tracker.update(instance, key, value)
                       if reset_tracker else value)
                counters[key] = counters.get(key, 0.0) + eff
            elif kind == "histogram":
                # Copy before dropping ``le`` — the parsed samples are
                # cached per instance and merged again on every payload.
                le = labels.get("le")
                labels = {k: v for k, v in labels.items() if k != "le"}
                key = _series_key(family, labels)
                raw_key = _series_key(name, {**labels, "le": le or ""})
                eff = (reset_tracker.update(instance, raw_key, value)
                       if reset_tracker else value)
                series = hists.setdefault(family, {}).setdefault(
                    key, {"buckets": {}, "sum": 0.0, "count": 0.0})
                if name.endswith("_bucket") and le is not None:
                    series["buckets"][le] = \
                        series["buckets"].get(le, 0.0) + eff
                elif name.endswith("_sum"):
                    series["sum"] += eff
                elif name.endswith("_count"):
                    series["count"] += eff
            elif kind == "gauge":
                # Never merged: the per-instance reading IS the datum.
                gauges[_series_key(
                    name, {**labels, "instance": instance})] = value
    return {"counters": counters, "gauges": gauges, "histograms": hists,
            "types": all_types}


def histogram_quantile(buckets: Dict[str, float], q: float) -> float:
    """Bucket-interpolated quantile over merged cumulative buckets (the
    same estimator as ``Histogram.quantile``, on the merged view)."""
    pairs = sorted(
        ((float("inf") if le == "+Inf" else float(le)), c)
        for le, c in buckets.items())
    if not pairs or pairs[-1][1] <= 0:
        return 0.0
    total = pairs[-1][1]
    target = q * total
    lo, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum >= target and cum > prev_cum:
            if le == float("inf"):
                return lo
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + (le - lo) * min(max(frac, 0.0), 1.0)
        lo, prev_cum = (le if le != float("inf") else lo), cum
    return lo


def fleet_instances_from_env(env=None) -> List[str]:
    import os

    raw = (env or os.environ).get("PIO_FLEET_INSTANCES", "")
    return [u.strip().rstrip("/") for u in raw.split(",") if u.strip()]


class _InstanceState:
    __slots__ = ("url", "types", "samples", "stats", "timeline",
                 "quality", "last_ok_at", "error")

    def __init__(self, url: str):
        self.url = url
        self.types: Dict[str, str] = {}
        self.samples: List[Tuple] = []
        self.stats: Optional[Dict[str, Any]] = None
        self.timeline: Optional[Dict[str, Any]] = None
        self.quality: Optional[Dict[str, Any]] = None
        self.last_ok_at: Optional[float] = None
        self.error: Optional[str] = None


class FleetAggregator:
    """Scrape + merge telemetry from a list of instance base URLs.

    One aggregator instance lives on the dashboard server (and one per
    ``pio status --fleet`` invocation); it keeps the counter-reset state
    and each instance's last-known-good scrape so a dead instance shows
    up stale instead of silently vanishing from the sums."""

    def __init__(self, instances: Iterable[str], *,
                 timeout_s: float = 5.0,
                 fetch=None,
                 clock=time.monotonic):
        self.instances = [u.rstrip("/") for u in instances]
        self.timeout_s = timeout_s
        self._fetch = fetch or self._http_fetch
        self._clock = clock
        self._resets = CounterResetTracker()
        self._state: Dict[str, _InstanceState] = {
            u: _InstanceState(u) for u in self.instances}
        self._lock = threading.Lock()
        self._scrape_pool: Optional[ThreadPoolExecutor] = None

    def _http_fetch(self, url: str) -> str:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8", errors="replace")

    def scrape_once(self) -> None:
        """One scrape pass over every instance (errors recorded, never
        raised).  Instances are fetched CONCURRENTLY — a dead instance
        costs one timeout for the whole pass, not one per instance, so a
        /fleet.json poll never serially stacks timeouts on its handler
        thread."""
        def _scrape(url: str) -> None:
            st = self._state[url]
            try:
                text = self._fetch(f"{url}/metrics")
                types, samples = parse_exposition(text)
                stats = None
                try:
                    stats = json.loads(self._fetch(f"{url}/stats.json"))
                except Exception:  # noqa: BLE001 - stats are optional
                    pass
                timeline = None
                try:
                    timeline = json.loads(self._fetch(
                        f"{url}/timeline.json?format=summary"))
                except Exception:  # noqa: BLE001 - timeline is optional
                    pass
                quality = None
                try:
                    quality = json.loads(self._fetch(
                        f"{url}/quality.json"))
                except Exception:  # noqa: BLE001 - quality is optional
                    pass
                with self._lock:
                    st.types, st.samples = types, samples
                    st.stats, st.timeline = stats, timeline
                    st.quality = quality if isinstance(quality, dict) \
                        else None
                    st.last_ok_at = self._clock()
                    st.error = None
            except Exception as e:  # noqa: BLE001 - degrade to stale
                with self._lock:
                    st.error = f"{type(e).__name__}: {e}"
                logger.warning("fleet scrape of %s failed: %s", url, e)

        if len(self.instances) <= 1:
            for url in self.instances:
                _scrape(url)
            return
        list(self._pool().map(_scrape, self.instances))

    def _pool(self) -> ThreadPoolExecutor:
        """Persistent scrape pool, created on first multi-instance pass —
        a dashboard polling /fleet.json at 1 Hz must not spawn and join
        N threads per request."""
        with self._lock:
            if self._scrape_pool is None:
                self._scrape_pool = ThreadPoolExecutor(
                    max_workers=min(len(self.instances), 16),
                    thread_name_prefix="pio-fleet-scrape")
            return self._scrape_pool

    def payload(self) -> Dict[str, Any]:
        """The ``/fleet.json`` document from the current state."""
        now = self._clock()
        with self._lock:
            states = {u: (st.types, list(st.samples))
                      for u, st in self._state.items() if st.samples}
            quality_docs = [st.quality for u in self.instances
                            for st in (self._state[u],)
                            if st.quality is not None]
            rows = []
            for u in self.instances:
                st = self._state[u]
                stale = st.error is not None or st.last_ok_at is None
                row: Dict[str, Any] = {
                    "instance": u,
                    "stale": stale,
                    "ageS": (round(now - st.last_ok_at, 1)
                             if st.last_ok_at is not None else None),
                }
                if st.error:
                    row["error"] = st.error
                if st.stats:
                    if "slo" in st.stats:
                        row["slo"] = st.stats["slo"]
                    if "batcher" in st.stats:
                        row["batcher"] = st.stats["batcher"]
                if st.timeline:
                    row["timeline"] = st.timeline.get("models")
                if st.quality is not None:
                    row["quality"] = st.quality
                rows.append(row)
            # Merge INSIDE the lock: the reset tracker mutates on every
            # merge, so a concurrent /fleet.json working from an older
            # snapshot after a fresh scrape advanced the tracker would
            # read its lower raw values as instance restarts and bank
            # phantom offsets — permanently inflating the fleet sums.
            merged = merge_samples(states, self._resets)
        quantiles = {
            fam: {key: {"p50": round(histogram_quantile(s["buckets"], .5), 3),
                        "p99": round(histogram_quantile(s["buckets"], .99), 3),
                        "count": s["count"]}
                  for key, s in series.items()}
            for fam, series in merged["histograms"].items()}
        # Quality merge (ISSUE 11): union-of-keys recursion — an
        # instance's field is never silently dropped (tier-1 pinned).
        # The recall block (ISSUE 16) rides the same merge: counts sum,
        # recallFast/recallSlow/baseline take the WORST instance (min).
        from predictionio_tpu.obs.quality import merge_quality

        return {
            "scrapedAt": round(time.time(), 3),
            "instances": rows,
            "merged": {
                "counters": {k: v for k, v in
                             sorted(merged["counters"].items())},
                "gauges": {k: v for k, v in
                           sorted(merged["gauges"].items())},
                "histogramQuantiles": quantiles,
                "histograms": merged["histograms"],
                "quality": merge_quality(quality_docs),
            },
        }

    def scrape(self) -> Dict[str, Any]:
        """scrape_once + payload — the dashboard's GET /fleet.json."""
        self.scrape_once()
        return self.payload()

    def close(self) -> None:
        """Release the persistent scrape pool.  Long-lived owners (the
        dashboard) never need this; short-lived ones (a rollout
        controller, `pio status --fleet`) should not leak a thread pool
        per invocation."""
        with self._lock:
            pool, self._scrape_pool = self._scrape_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
