"""Engine: the assembled DASE pipeline + engine.json variant parsing.

Reference: core/.../controller/Engine.scala (train/eval drive),
EngineFactory, EngineParams; the engine.json schema is preserved verbatim
(SURVEY.md Appendix A)::

    {"id"?, "description"?, "engineFactory",
     "datasource": {"params": {...}},
     "preparator": {"params": {...}},
     "algorithms": [{"name": ..., "params": {...}}, ...],
     "serving": {"params": {...}}}
"""

from __future__ import annotations

import dataclasses
import importlib
import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from predictionio_tpu.controller.base import (
    Algorithm,
    DataSource,
    FirstServing,
    IdentityPreparator,
    Preparator,
    RuntimeContext,
    Serving,
)
from predictionio_tpu.controller.params import (
    Params,
    ParamsBindingError,
    bind_params,
    params_to_dict,
)

__all__ = ["Engine", "EngineParams", "EngineVariant", "EvalCheckpoint",
           "load_engine_factory"]


class EvalCheckpoint:
    """Fold-granular eval-sweep checkpoints (ISSUE 15 satellite, carried
    since PR 7's eval rewire).

    ``pio eval`` sweeps are candidates × folds of full trains; a
    SIGTERM'd sweep used to restart from scratch.  One completed
    ``(candidate, fold)`` unit = one pickle file in ``directory``; on
    resume :meth:`Engine.eval_multi` loads completed units instead of
    retraining them.  Validity rests on the same determinism contract as
    train resume: the SAME evaluation command (same candidates, same
    seeds) produces the same fold split, so unit (ci, fi) means the same
    work across runs — a changed sweep should use a fresh directory."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _path(self, candidate: int, fold: int) -> Path:
        return self.dir / f"cand{candidate:04d}_fold{fold:04d}.pkl"

    def has(self, candidate: int, fold: int) -> bool:
        return self._path(candidate, fold).exists()

    def get(self, candidate: int, fold: int):
        import pickle

        with open(self._path(candidate, fold), "rb") as f:
            return pickle.load(f)

    def put(self, candidate: int, fold: int, result) -> None:
        import pickle

        tmp = self._path(candidate, fold).with_suffix(".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(result, f)
        tmp.replace(self._path(candidate, fold))  # atomic: never torn

    def completed(self) -> int:
        return len(list(self.dir.glob("cand*_fold*.pkl")))

    def clear(self) -> None:
        for p in self.dir.glob("cand*_fold*.pkl"):
            p.unlink(missing_ok=True)


@dataclasses.dataclass
class EngineParams:
    """One full parameterization of an engine (reference: EngineParams)."""

    datasource_params: Optional[Params] = None
    preparator_params: Optional[Params] = None
    algorithms_params: Sequence[Tuple[str, Optional[Params]]] = ()
    serving_params: Optional[Params] = None

    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "datasource": {"params": params_to_dict(self.datasource_params)},
            "preparator": {"params": params_to_dict(self.preparator_params)},
            "algorithms": [
                {"name": name, "params": params_to_dict(p)}
                for name, p in self.algorithms_params
            ],
            "serving": {"params": params_to_dict(self.serving_params)},
        }


class Engine:
    """Binds DASE role classes into a trainable/servable pipeline.

    Reference: controller/Engine.scala — constructed by the user's
    EngineFactory with the datasource/preparator class, a named map of
    algorithm classes, and the serving class.
    """

    def __init__(
        self,
        datasource_class: Type[DataSource],
        preparator_class: Type[Preparator] = IdentityPreparator,
        algorithm_classes: Optional[Dict[str, Type[Algorithm]]] = None,
        serving_class: Type[Serving] = FirstServing,
        query_class: Optional[type] = None,
    ):
        self.datasource_class = datasource_class
        self.preparator_class = preparator_class
        self.algorithm_classes = dict(algorithm_classes or {})
        self.serving_class = serving_class
        # Query dataclass for the deploy server's JSON binding (reference:
        # the Query type param of Engine; JsonExtractor binds requests to it).
        self.query_class = query_class

    # -- engine.json binding ----------------------------------------------
    def bind_engine_params(self, variant_json: Dict[str, Any]) -> EngineParams:
        """Bind an engine.json variant's param blocks to typed Params."""

        def block(name: str) -> Dict[str, Any]:
            b = variant_json.get(name) or {}
            return b.get("params") or {}

        ds = bind_params(self.datasource_class.params_class, block("datasource"))
        prep = bind_params(self.preparator_class.params_class, block("preparator"))
        serving = bind_params(self.serving_class.params_class, block("serving"))
        algos: List[Tuple[str, Params]] = []
        specs = variant_json.get("algorithms")
        if specs is None:
            # Default: every registered algorithm with default params.
            specs = [{"name": n, "params": {}} for n in self.algorithm_classes]
        for spec in specs:
            name = spec.get("name")
            if name not in self.algorithm_classes:
                raise ParamsBindingError(
                    f"Unknown algorithm {name!r}; registered: "
                    f"{sorted(self.algorithm_classes)}"
                )
            cls = self.algorithm_classes[name]
            algos.append((name, bind_params(cls.params_class, spec.get("params") or {})))
        return EngineParams(
            datasource_params=ds,
            preparator_params=prep,
            algorithms_params=tuple(algos),
            serving_params=serving,
        )

    # -- instantiation -----------------------------------------------------
    def make_algorithms(self, engine_params: EngineParams) -> List[Algorithm]:
        return [
            self.algorithm_classes[name](params)
            for name, params in engine_params.algorithms_params
        ]

    def make_serving(self, engine_params: EngineParams) -> Serving:
        return self.serving_class(engine_params.serving_params)

    # -- train / eval drive (reference: Engine.train / Engine.eval) --------
    def train(self, ctx: RuntimeContext, engine_params: EngineParams,
              warm: Any = None) -> List[Any]:
        """Run DataSource → Preparator → each Algorithm.train; returns models.

        Each DASE stage is a named observability phase: a span in the
        enclosing ``run_train`` trace and a ``pio_train_phase_ms`` series.

        With ``warm`` (a :class:`~predictionio_tpu.refresh.
        WarmStartContext`; ISSUE 10), the datasource reads through the
        caller's delta-scoped event store and every algorithm continues
        its previous model via :meth:`Algorithm.warm_start` instead of
        :meth:`Algorithm.train`.  Any algorithm raising
        :class:`~predictionio_tpu.controller.WarmStartFallback` aborts the
        WHOLE warm attempt (one generation must be one consistent data
        window — a mixed warm/full model set would serve models trained
        on different corpora); ``run_train`` then retrains fully.
        """
        from predictionio_tpu.obs import phase

        names = [n for n, _ in engine_params.algorithms_params]
        if warm is not None:
            from predictionio_tpu.controller.base import (
                Algorithm as _AlgoBase,
                WarmStartFallback,
            )

            if len(warm.models) != len(names):
                raise WarmStartFallback(
                    f"algorithm set changed ({len(warm.models)} previous "
                    f"model(s) vs {len(names)} configured)")
            # Decline BEFORE the datasource read: an engine whose
            # algorithms all use the declining default (e.g. ALS) would
            # otherwise pay a full delta read+prepare every refresh
            # cycle just to be told no.
            if all(cls.warm_start is _AlgoBase.warm_start
                   for cls in self.algorithm_classes.values()):
                raise WarmStartFallback(
                    "no configured algorithm supports warm-start "
                    "continuation")
        datasource = self.datasource_class(engine_params.datasource_params)
        preparator = self.preparator_class(engine_params.preparator_params)
        with phase("train.datasource"):
            td = datasource.read_training(ctx)
        with phase("train.prepare"):
            pd = preparator.prepare(ctx, td)
        models = []
        for i, (name, algo) in enumerate(
                zip(names, self.make_algorithms(engine_params))):
            if warm is not None:
                with phase("train.algorithm.warm", algo=name):
                    models.append(
                        algo.warm_start(ctx, pd, warm.models[i], warm))
            else:
                with phase("train.algorithm", algo=name):
                    models.append(algo.train(ctx, pd))
        return models

    def eval(
        self, ctx: RuntimeContext, engine_params: EngineParams
    ) -> List[Tuple[Any, List[Tuple[Any, Any, Any]]]]:
        """K folds of (eval_info, [(query, predicted, actual)]).

        Reference: Engine.eval — readEval folds, train on each fold's
        training split, batch-predict the fold's queries through Serving.
        """
        return self.eval_multi(ctx, [engine_params])[0]

    def eval_multi(
        self, ctx: RuntimeContext,
        engine_params_list: Sequence[EngineParams],
        checkpoint: Optional["EvalCheckpoint"] = None,
    ) -> List[List[Tuple[Any, List[Tuple[Any, Any, Any]]]]]:
        """Shared-prep candidate sweep (round-2 verdict item 9).

        ``read_eval`` folds and ``Preparator.prepare`` run ONCE per
        distinct (datasource, preparator) param pair — the typical sweep
        varies only algorithm params, so N candidates cost one data pass
        plus N algorithm trains.  Compiled-program reuse across
        candidates is free on top: identical fold shapes hit the jit
        cache.  Returns per-candidate results aligned with the input.

        With ``checkpoint`` (ISSUE 15 satellite) every completed
        ``(candidate, fold)`` unit is persisted as it finishes, a
        pending SIGTERM raises
        :class:`~predictionio_tpu.resilience.supervision.TrainPreempted`
        BETWEEN units (a preemption inside a supervised ``train()``
        propagates the same way), and a rerun loads completed units
        instead of retraining them — the training preemption contract,
        extended to eval sweeps.
        """
        from predictionio_tpu.resilience.supervision import (
            TrainPreempted,
            preemption_requested,
        )

        results: List[Any] = [None] * len(engine_params_list)
        groups: Dict[str, List[int]] = {}
        for i, ep in enumerate(engine_params_list):
            key = repr((ep.datasource_params, ep.preparator_params))
            groups.setdefault(key, []).append(i)
        for idxs in groups.values():
            first = engine_params_list[idxs[0]]
            datasource = self.datasource_class(first.datasource_params)
            preparator = self.preparator_class(first.preparator_params)
            for ci in idxs:
                results[ci] = []
            # Fold OUTER, candidates inner: only ONE prepared fold is live
            # at a time (the old per-candidate eval held one fold too —
            # holding all K at once would be a memory regression).
            for fi, (td, eval_info, qa) in enumerate(
                    datasource.read_eval(ctx)):
                todo = [ci for ci in idxs
                        if checkpoint is None
                        or not checkpoint.has(ci, fi)]
                # Skip the fold's prepare entirely when a prior run
                # already finished every candidate on it.
                pd = preparator.prepare(ctx, td) if todo else None
                for ci in idxs:
                    if ci not in todo:
                        results[ci].append(checkpoint.get(ci, fi))
                        continue
                    if checkpoint is not None and preemption_requested():
                        # fn/step carry the sweep coordinates; the
                        # "checkpointed" flag is honest — every finished
                        # unit is already on disk.
                        raise TrainPreempted(
                            f"eval sweep (candidate {ci} fold {fi}, "
                            f"{checkpoint.completed()} unit(s) saved)",
                            step=fi,
                            checkpointed=checkpoint.completed() > 0)
                    engine_params = engine_params_list[ci]
                    serving = self.make_serving(engine_params)
                    algos = self.make_algorithms(engine_params)
                    models = [a.train(ctx, pd) for a in algos]
                    indexed = list(enumerate(q for q, _ in qa))
                    per_algo: List[Dict[int, Any]] = []
                    for a, m in zip(algos, models):
                        per_algo.append(dict(_eval_batch_predict(
                            a, m, indexed)))
                    qpa = []
                    for i, (q, actual) in enumerate(qa):
                        predictions = [pa[i] for pa in per_algo]
                        qpa.append((q, serving.serve(q, predictions),
                                    actual))
                    results[ci].append((eval_info, qpa))
                    if checkpoint is not None:
                        checkpoint.put(ci, fi, (eval_info, qpa))
        return results


def _eval_chunk_size(default: int = 1024) -> int:
    """``PIO_EVAL_BATCH``: queries per eval ``batch_predict`` dispatch
    (0 disables chunking — one monolithic batch, the pre-ISSUE-7
    behavior)."""
    import os

    try:
        return int(os.environ.get("PIO_EVAL_BATCH", str(default)))
    except ValueError:
        return default


def _eval_batch_predict(algo: Algorithm, model: Any,
                        indexed: Sequence[Tuple[int, Any]]):
    """Stream an eval fold's queries through the shared input-staging
    path (ISSUE 7 satellite).

    ``pio eval`` used to hand ``batch_predict`` the WHOLE fold in one
    inline call — its own input path, with an unbounded [B, N] score
    block for big folds.  Now the fold streams in ``PIO_EVAL_BATCH``
    chunks through :class:`~predictionio_tpu.data.prefetch.
    DevicePrefetcher` — the same staging machinery (lifecycle, queue
    gauges, prep-thread exception propagation) the train loops ride.
    The real win here is the bounded peak memory; the fold's queries are
    already materialized before prediction and each ``batch_predict``
    stages + dispatches internally, so the prep thread only slices —
    there is no train-style H2D overlap to claim.  Per-query results
    are unchanged (each chunk's padded batch covers every member
    query's ``num``).
    """
    chunk = _eval_chunk_size()
    if chunk <= 0 or len(indexed) <= chunk:
        yield from algo.batch_predict(model, list(indexed))
        return
    from predictionio_tpu.data.prefetch import DevicePrefetcher

    def chunks():
        for start in range(0, len(indexed), chunk):
            yield list(indexed[start:start + chunk])

    with DevicePrefetcher(chunks(), lambda c: c,
                          put_fn=lambda c: c,
                          count_fn=len) as pf:
        for staged in pf:
            yield from algo.batch_predict(model, staged.args)


@dataclasses.dataclass
class EngineVariant:
    """A parsed engine.json file (reference: engine variant manifest)."""

    engine_factory: str
    variant_id: str = "default"
    description: str = ""
    raw: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_file(path) -> "EngineVariant":
        raw = json.loads(Path(path).read_text())
        return EngineVariant.from_dict(raw)

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "EngineVariant":
        if "engineFactory" not in raw:
            raise ParamsBindingError("engine.json must declare engineFactory.")
        return EngineVariant(
            engine_factory=raw["engineFactory"],
            variant_id=raw.get("id", "default"),
            description=raw.get("description", ""),
            raw=raw,
        )


def load_engine_factory(dotted: str):
    """Resolve an engineFactory string to a callable returning an Engine.

    Reference: WorkflowUtils.getEngine — reflective class load.  Accepted
    forms: ``package.module:factory_fn`` or ``package.module.factory_fn``.
    """
    if ":" in dotted:
        mod_name, attr = dotted.split(":", 1)
    else:
        mod_name, _, attr = dotted.rpartition(".")
        if not mod_name:
            raise ParamsBindingError(f"Invalid engineFactory {dotted!r}.")
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        raise ParamsBindingError(f"Cannot import engineFactory module {mod_name!r}: {e}") from e
    try:
        factory = getattr(mod, attr)
    except AttributeError:
        raise ParamsBindingError(
            f"Module {mod_name!r} has no attribute {attr!r} (engineFactory)."
        ) from None
    return factory
