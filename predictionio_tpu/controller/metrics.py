"""Evaluation metrics + Evaluation/EngineParamsGenerator contracts.

Reference: core/.../controller/Metric.scala (AverageMetric,
OptionAverageMetric, SumMetric), Evaluation.scala, MetricEvaluator.scala,
EngineParamsGenerator.scala (SURVEY.md §2.1, §3.4).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Generic, List, Optional, Sequence, Tuple, TypeVar

__all__ = [
    "Metric",
    "AverageMetric",
    "OptionAverageMetric",
    "SumMetric",
    "ZeroMetric",
    "Evaluation",
    "EngineParamsGenerator",
    "MetricEvaluatorResult",
]

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")


class Metric(Generic[EI, Q, P, A], abc.ABC):
    """Reference: Metric — scores a full eval data set.

    ``eval_data``: folds of (eval_info, [(query, predicted, actual)]).
    Higher is better by default (reference: Ordering on the result).
    """

    @abc.abstractmethod
    def calculate(self, eval_data: Sequence[Tuple[EI, List[Tuple[Q, P, A]]]]) -> float: ...

    def compare(self, a: float, b: float) -> int:
        return (a > b) - (a < b)

    @property
    def header(self) -> str:
        return type(self).__name__


class AverageMetric(Metric[EI, Q, P, A]):
    """Mean of a per-(q,p,a) score over all folds (reference: AverageMetric)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> float: ...

    def calculate(self, eval_data) -> float:
        scores = [
            self.calculate_one(q, p, a)
            for _, qpa in eval_data
            for q, p, a in qpa
        ]
        return sum(scores) / len(scores) if scores else float("nan")


class OptionAverageMetric(Metric[EI, Q, P, A]):
    """Mean over non-None per-row scores (reference: OptionAverageMetric)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> Optional[float]: ...

    def calculate(self, eval_data) -> float:
        scores = [
            s
            for _, qpa in eval_data
            for q, p, a in qpa
            if (s := self.calculate_one(q, p, a)) is not None
        ]
        return sum(scores) / len(scores) if scores else float("nan")


class SumMetric(Metric[EI, Q, P, A]):
    """Sum of per-row scores (reference: SumMetric)."""

    @abc.abstractmethod
    def calculate_one(self, query: Q, predicted: P, actual: A) -> float: ...

    def calculate(self, eval_data) -> float:
        return sum(
            self.calculate_one(q, p, a) for _, qpa in eval_data for q, p, a in qpa
        )


class ZeroMetric(Metric):
    """Reference: ZeroMetric — placeholder that always scores 0."""

    def calculate(self, eval_data) -> float:
        return 0.0


@dataclasses.dataclass
class Evaluation:
    """Reference: Evaluation — pairs an Engine with metric(s).

    ``engine_factory`` is kept as the dotted string so eval runs are
    reproducible from metadata alone (like the reference's class names in
    EvaluationInstance rows).
    """

    engine: Any                      # controller.Engine
    metric: Metric
    other_metrics: Sequence[Metric] = ()

    @property
    def metrics(self) -> List[Metric]:
        return [self.metric, *self.other_metrics]


class EngineParamsGenerator(abc.ABC):
    """Reference: EngineParamsGenerator — the sweep candidates."""

    @property
    @abc.abstractmethod
    def engine_params_list(self) -> Sequence[Any]: ...


@dataclasses.dataclass
class MetricEvaluatorResult:
    """Reference: MetricEvaluator.Result — best params + per-candidate scores."""

    best_score: float
    best_engine_params: Any
    best_index: int
    metric_header: str
    other_metric_headers: List[str]
    candidate_scores: List[Tuple[Any, float, List[float]]]  # (params, score, others)

    def summary(self) -> str:
        lines = [
            "MetricEvaluatorResult:",
            f"  # engine params evaluated: {len(self.candidate_scores)}",
            f"Optimal Engine Params (index {self.best_index}):",
        ]
        import json

        lines.append(
            "  " + json.dumps(self.best_engine_params.to_json_dict(), indent=2).replace("\n", "\n  ")
        )
        lines.append(f"Metrics:")
        lines.append(f"  {self.metric_header}: {self.best_score}")
        for h, s in zip(self.other_metric_headers,
                        self.candidate_scores[self.best_index][2]):
            lines.append(f"  {h}: {s}")
        return "\n".join(lines)
