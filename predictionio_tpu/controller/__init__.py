"""Controller API — the user-facing engine SDK (DASE).

Reference: core/src/main/scala/org/apache/predictionio/controller/
(SURVEY.md §2.1 "Controller API").  Engine authors import from here::

    from predictionio_tpu.controller import (
        DataSource, Preparator, Algorithm, Serving, Engine, Params, ...
    )
"""

from predictionio_tpu.controller.base import (
    Algorithm,
    DataSource,
    FirstServing,
    IdentityPreparator,
    PersistentModel,
    Preparator,
    RuntimeContext,
    Serving,
    WarmStartFallback,
    model_from_bytes,
    model_to_bytes,
)
from predictionio_tpu.controller.engine import (
    Engine,
    EngineParams,
    EngineVariant,
    load_engine_factory,
)
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    EngineParamsGenerator,
    Evaluation,
    Metric,
    MetricEvaluatorResult,
    OptionAverageMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.controller.params import (
    EmptyParams,
    Params,
    ParamsBindingError,
    bind_params,
    params_to_dict,
)

__all__ = [
    "Algorithm",
    "AverageMetric",
    "DataSource",
    "EmptyParams",
    "Engine",
    "EngineParams",
    "EngineParamsGenerator",
    "EngineVariant",
    "Evaluation",
    "FirstServing",
    "IdentityPreparator",
    "Metric",
    "MetricEvaluatorResult",
    "OptionAverageMetric",
    "Params",
    "ParamsBindingError",
    "PersistentModel",
    "Preparator",
    "RuntimeContext",
    "Serving",
    "SumMetric",
    "WarmStartFallback",
    "ZeroMetric",
    "bind_params",
    "load_engine_factory",
    "model_from_bytes",
    "model_to_bytes",
    "params_to_dict",
]
