"""DASE base classes — the engine-author SDK.

Reference: core/src/main/scala/org/apache/predictionio/controller/ over the
SPI in core/.../core/ (BaseDataSource, BasePreparator, BaseAlgorithm,
BaseServing, BaseEvaluator — SURVEY.md §2.1).

Substrate mapping: where the reference passes a ``SparkContext`` as the
first argument of every role, we pass a :class:`RuntimeContext` carrying the
storage handle, the event store, and the JAX device mesh.  The reference's
``P*``/``L*`` split (RDD vs local collections) collapses: training data is
whatever the DataSource returns — typically columnar arrays destined for
sharded ``jax.Array`` construction.
"""

from __future__ import annotations

import abc
import dataclasses
import pickle
from typing import Any, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

from predictionio_tpu.controller.params import EmptyParams, Params

__all__ = [
    "RuntimeContext",
    "DataSource",
    "Preparator",
    "IdentityPreparator",
    "Algorithm",
    "Serving",
    "FirstServing",
    "PersistentModel",
    "WarmStartFallback",
    "model_to_bytes",
    "model_from_bytes",
]


class WarmStartFallback(Exception):
    """A warm-start (delta) train cannot proceed — fall back to a full
    retrain (ISSUE 10).

    Raised by :meth:`Algorithm.warm_start` when the algorithm does not
    support incremental continuation, when the delta window is too large
    a fraction of the corpus for continuation to be trustworthy, or when
    the warm-started model regresses against the generation it started
    from.  ``run_train`` catches it and re-runs the engine in full mode
    over the complete window — the refresh always lands a generation,
    just a more expensive one.
    """

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason

TD = TypeVar("TD")   # training data
PD = TypeVar("PD")   # prepared data
M = TypeVar("M")     # model
Q = TypeVar("Q")     # query
P = TypeVar("P")     # predicted result
A = TypeVar("A")     # actual result
EI = TypeVar("EI")   # evaluation info


@dataclasses.dataclass
class RuntimeContext:
    """What a DASE role gets instead of the reference's SparkContext.

    - ``storage``: the configured :class:`~predictionio_tpu.data.storage.Storage`
    - ``event_store``: name-resolving read API
      (:class:`~predictionio_tpu.data.store.EventStore`)
    - ``mesh``: the JAX device mesh for sharded compute (None = single device)
    - ``seed``: base RNG seed for the run (reproducible training)
    """

    storage: Any = None
    event_store: Any = None
    mesh: Any = None
    seed: int = 0
    workflow_params: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def create(
        storage=None,
        mesh=None,
        seed: int = 0,
        mesh_spec: Optional[str] = None,
        **workflow_params,
    ) -> "RuntimeContext":
        """Build the run context; this is where multi-chip bring-up happens.

        ``mesh_spec`` (or env ``PIO_MESH``, e.g. ``data=8,model=2`` /
        ``auto``) constructs the device mesh every sharded model trains
        over; multi-host gangs join first via ``initialize_distributed``
        (env ``PIO_COORDINATOR_ADDRESS``).  Reference: where Spark's
        context creation happened in CoreWorkflow (SURVEY.md §3.1), mesh
        construction happens here — engines only consume ``ctx.mesh``.
        """
        import os

        from predictionio_tpu.data.store import EventStore
        from predictionio_tpu.data.storage import get_storage

        if mesh is None:
            spec = mesh_spec if mesh_spec is not None else os.environ.get("PIO_MESH")
            if spec:
                from predictionio_tpu.parallel.distributed import initialize_distributed
                from predictionio_tpu.parallel.mesh import mesh_from_spec

                initialize_distributed()
                mesh = mesh_from_spec(spec)
        storage = storage or get_storage()
        return RuntimeContext(
            storage=storage,
            event_store=EventStore(storage),
            mesh=mesh,
            seed=seed,
            workflow_params=dict(workflow_params),
        )


class _HasParams:
    """Every DASE role is constructed with its Params (reference: Doer)."""

    params_class: type = EmptyParams

    def __init__(self, params: Optional[Params] = None):
        self.params = params if params is not None else self.params_class()


class DataSource(_HasParams, Generic[TD, EI, Q, A], abc.ABC):
    """Reference: PDataSource/LDataSource — reads training and eval data."""

    @abc.abstractmethod
    def read_training(self, ctx: RuntimeContext) -> TD: ...

    def read_eval(self, ctx: RuntimeContext) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
        """K folds of (training data, eval info, [(query, actual)]).

        Reference: PDataSource.readEval.  Default: no eval support.
        """
        return []


class Preparator(_HasParams, Generic[TD, PD], abc.ABC):
    """Reference: PPreparator/LPreparator."""

    @abc.abstractmethod
    def prepare(self, ctx: RuntimeContext, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator[TD, TD]):
    """Reference: IdentityPreparator — passes training data through."""

    def prepare(self, ctx: RuntimeContext, training_data: TD) -> TD:
        return training_data


class Algorithm(_HasParams, Generic[PD, M, Q, P], abc.ABC):
    """Reference: PAlgorithm/P2LAlgorithm/LAlgorithm.

    The three reference flavors differ only in where the model lives (RDD vs
    local); on TPU the model is (sharded) ``jax.Array`` pytrees either way,
    so one class suffices.
    """

    @abc.abstractmethod
    def train(self, ctx: RuntimeContext, prepared_data: PD) -> M: ...

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P: ...

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]) -> List[Tuple[int, P]]:
        """Reference: PAlgorithm.batchPredict (used by evaluation).

        Default maps :meth:`predict`; algorithms override with a vectorized
        XLA path when the per-query loop matters.
        """
        return [(i, self.predict(model, q)) for i, q in queries]

    def warm_start(self, ctx: RuntimeContext, prepared_delta: PD,
                   prev_model: M, warm: Any) -> M:
        """Continue training ``prev_model`` on a DELTA window of prepared
        data (ISSUE 10: event-delta warm-start refresh).

        ``prepared_delta`` was read through a window-scoped event store
        covering only ``(previous generation's watermark, new
        watermark]``; ``warm`` is the
        :class:`~predictionio_tpu.refresh.WarmStartContext` carrying the
        window and the fallback thresholds.  Implementations must either
        return a model trained on previous-state + delta, or raise
        :class:`WarmStartFallback` — the workflow then re-runs the whole
        engine in full mode (delta too large, regressed eval, missing
        carried state, ...).  The default declines: algorithms without an
        incremental form (e.g. ALS, which gets serve-time fold-in
        instead) always retrain fully on refresh.
        """
        raise WarmStartFallback(
            f"{type(self).__name__} does not support warm-start "
            "continuation")


class Serving(_HasParams, Generic[Q, P], abc.ABC):
    """Reference: LServing — combine predictions of all algorithms."""

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P: ...

    def supplement(self, query: Q) -> Q:
        """Reference: LServing.supplement hook — enrich query pre-predict."""
        return query


class FirstServing(Serving[Q, P]):
    """Reference: FirstServing — returns the first algorithm's prediction."""

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        return predictions[0]


class PersistentModel(abc.ABC):
    """Opt-in custom model persistence (reference: PersistentModel +
    PersistentModelLoader).

    Models that don't implement this are pickled into the MODELDATA blob
    store keyed by engine-instance id.  Implement for sharded/orbax
    checkpoints that shouldn't round-trip through a single blob.
    """

    @abc.abstractmethod
    def save(self, instance_id: str, ctx: RuntimeContext) -> bool:
        """Persist under ``instance_id``; return False to fall back to pickle."""

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params, ctx: RuntimeContext) -> "PersistentModel":
        ...


def model_to_bytes(model: Any) -> bytes:
    """Default model serialization (reference: P2L/L auto-persistence).

    JAX arrays pickle fine via numpy conversion done by their reducers;
    engines with exotic state implement PersistentModel instead.
    """
    return pickle.dumps(model, protocol=pickle.HIGHEST_PROTOCOL)


def model_from_bytes(blob: bytes) -> Any:
    return pickle.loads(blob)
