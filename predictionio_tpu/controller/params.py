"""Typed parameter binding: engine.json → dataclass Params.

Reference: core/.../workflow/JsonExtractor.scala — binds the ``engine.json``
variant's ``datasource`` / ``preparator`` / ``algorithms[]`` / ``serving``
param blocks onto typed case classes, erroring on type mismatches.  Here
"case class" is a Python dataclass; binding is strict: unknown keys and
type mismatches raise :class:`ParamsBindingError`.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Mapping, Optional, Type, TypeVar

__all__ = ["Params", "EmptyParams", "ParamsBindingError", "bind_params", "params_to_dict"]


class ParamsBindingError(TypeError):
    pass


@dataclasses.dataclass(frozen=True)
class Params:
    """Marker base for engine parameter dataclasses (reference: Params trait).

    Subclass with ``@dataclass(frozen=True)`` fields; defaults become
    optional engine.json keys.
    """


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """Reference: EmptyParams — roles that take no parameters."""


T = TypeVar("T", bound=Params)


def _coerce(value: Any, annot: Any, path: str) -> Any:
    origin = typing.get_origin(annot)
    if annot is Any or annot is dataclasses.MISSING or annot is None:
        return value
    if origin is typing.Union:  # includes Optional[X]
        args = typing.get_args(annot)
        if value is None:
            if type(None) in args:
                return None
            raise ParamsBindingError(f"{path}: null not allowed for {annot}.")
        non_none = [a for a in args if a is not type(None)]
        last_err: Optional[Exception] = None
        for a in non_none:
            try:
                return _coerce(value, a, path)
            except ParamsBindingError as e:
                last_err = e
        raise ParamsBindingError(f"{path}: {value!r} matches no arm of {annot}.") from last_err
    if origin in (list, tuple):
        if not isinstance(value, (list, tuple)):
            raise ParamsBindingError(f"{path}: expected list, got {type(value).__name__}.")
        args = typing.get_args(annot)
        elem = args[0] if args else Any
        seq = [_coerce(v, elem, f"{path}[{i}]") for i, v in enumerate(value)]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        if not isinstance(value, Mapping):
            raise ParamsBindingError(f"{path}: expected object, got {type(value).__name__}.")
        kt, vt = (typing.get_args(annot) + (Any, Any))[:2]
        return {
            _coerce(k, kt, f"{path}.<key>"): _coerce(v, vt, f"{path}.{k}")
            for k, v in value.items()
        }
    if dataclasses.is_dataclass(annot):
        if not isinstance(value, Mapping):
            raise ParamsBindingError(f"{path}: expected object for nested params.")
        return bind_params(annot, value, _path=path)
    if annot is bool:
        if not isinstance(value, bool):
            raise ParamsBindingError(f"{path}: expected bool, got {type(value).__name__}.")
        return value
    if annot is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ParamsBindingError(f"{path}: expected int, got {type(value).__name__}.")
        return value
    if annot is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsBindingError(f"{path}: expected number, got {type(value).__name__}.")
        return float(value)
    if annot is str:
        if not isinstance(value, str):
            raise ParamsBindingError(f"{path}: expected string, got {type(value).__name__}.")
        return value
    return value


_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _type_hints(cls: type) -> Dict[str, Any]:
    """Cached ``typing.get_type_hints``: evaluating annotations was 23% of
    the serving hot path (it re-compiles every string annotation per call;
    query classes are bound once per REQUEST)."""
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = _HINTS_CACHE[cls] = typing.get_type_hints(cls)
    return hints


def bind_params(cls: Type[T], data: Optional[Mapping[str, Any]], _path: str = "params") -> T:
    """Bind a JSON object onto a Params dataclass, strictly."""
    if not dataclasses.is_dataclass(cls):
        raise ParamsBindingError(f"{cls!r} is not a dataclass Params type.")
    data = dict(data or {})
    hints = _type_hints(cls)
    kwargs: Dict[str, Any] = {}
    # Python-reserved-word aliasing: the reference's engine.json spells
    # e.g. ALS regParam as "lambda"; the dataclass field is "lambda_".
    for f in dataclasses.fields(cls):
        if f.name.endswith("_") and f.name[:-1] in data \
                and f.name not in data:
            data[f.name] = data.pop(f.name[:-1])
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(data.pop(f.name), hints.get(f.name, Any), f"{_path}.{f.name}")
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING  # type: ignore[misc]
        ):
            raise ParamsBindingError(f"{_path}.{f.name} is required for {cls.__name__}.")
    if data:
        raise ParamsBindingError(
            f"{_path}: unknown keys {sorted(data)} for {cls.__name__} "
            f"(known: {[f.name for f in dataclasses.fields(cls)]})."
        )
    return cls(**kwargs)


def params_to_dict(params: Any) -> Dict[str, Any]:
    """Serialize Params back to a JSON-able dict (for EngineInstance rows)."""
    if params is None:
        return {}
    if dataclasses.is_dataclass(params):
        return json.loads(json.dumps(dataclasses.asdict(params)))
    if isinstance(params, Mapping):
        return dict(params)
    raise ParamsBindingError(f"Cannot serialize params of type {type(params).__name__}.")
