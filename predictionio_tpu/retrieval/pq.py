"""Product-quantized corpora — the memory side of ANN (ISSUE 13).

IVF (retrieval/ivf.py) made the scan sublinear, but the exact fp32
vectors still live in device memory: 1e6 items × 64 dims is 256 MB and
1e7 is an OOM on one chip.  Residual product quantization shrinks the
resident corpus 10–100×: each vector is a coarse centroid (1 byte) plus
``M`` per-subspace codebook entries (1 byte each), so a D=32 f32 row
(128 B) becomes 9 B at M=8.  Serving scores codes asymmetrically — the
query stays exact, per-query lookup tables (LUTs) of
``query · codebook-entry`` inner products are built once and the score
of item ``n`` is ``Σ_m lut[m, codes[n, m]]``, which is exactly
``q · decode(n)`` — then a shortlist of ``rerank`` candidates is
re-scored against the exact embeddings so recall never rides the
quantization error.

Design points:

- **Residual on top of the coarse quantizer.**  Codes quantize
  ``x − coarse[c0(x)]``, not ``x``: residual energy is a fraction of
  vector energy, so the same byte budget buys a much tighter
  reconstruction.  When the generation carries an IVF index the coarse
  book is derived FROM its centroids (reused outright at nlist ≤ 256,
  else the 256 heaviest-list centroids refined by Lloyd iterations on
  the raw vectors) — PQ sits on top of the existing coarse structure
  instead of fighting it.
- **Uniform [1+M, 256] tables.**  The coarse book is stored padded to
  256 rows, so the coarse term is just table 0 of the LUT stack and the
  device scan (``ops.pallas_kernels.pq_scan``) sees one [B, S, 256]
  VMEM-resident block and one packed [S, N] uint8 code matrix — no
  special cases, no ragged shapes.
- **Exact re-rank holds recall.**  PQ scores ORDER a shortlist of
  ``rerank`` (default 4·k, ``PIO_PQ_RERANK``) candidates; the returned
  top-k is always computed from exact inner products over those
  candidates (fp32, or a bf16/int8 staged copy under
  ``PIO_CORPUS_DTYPE``).  This is what makes quantization safe for
  norm-variant corpora (raw ALS factors) where IVF alone is not.
- **Versioned with the generation.**  The codebook carries the SAME
  SHA-1 corpus fingerprint as the IVF index and travels INSIDE the
  pickled model wrapper — the staged-reload/rollback swap moves
  codes+index+model atomically, and the facade drops a mismatched
  codebook loudly (exact serving continues, never silently wrong
  results).

Knobs: ``PIO_PQ`` (auto|on|off — build policy), ``PIO_PQ_M``
(subspaces, default ~D/4 rounded to a divisor), ``PIO_PQ_MIN_ITEMS``
(exact-only below, default 200k), ``PIO_PQ_RERANK`` (shortlist size,
default 4·k), ``PIO_CORPUS_DTYPE`` (f32|bf16|int8 re-rank corpus).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from functools import partial
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.retrieval.ivf import IVFIndex, corpus_fingerprint

logger = logging.getLogger(__name__)

__all__ = ["PQCodebook", "build_pq", "pq_build_config", "lut_tables",
           "decode_pq", "search_pq_host", "search_ivf_pq_host",
           "search_pq_device", "search_ivf_pq_device", "quantize_int8",
           "DEFAULT_PQ_MIN_ITEMS"]

DEFAULT_PQ_MIN_ITEMS = 200_000
_NEG_INF = np.float32(-3.4e38)
_SENTINEL = -1e37  # at/below = padding (matches the facade's sentinel)


@dataclasses.dataclass
class PQCodebook:
    """Residual PQ codes + codebooks over one item corpus.

    Pickled inside the model wrapper next to the IVF index — model,
    index and codes are ONE serialized artifact, so a generation swap
    can never mix them.  ``codes`` column 0 is the coarse assignment;
    columns ``1..M`` the per-subspace residual codes.
    """

    coarse: np.ndarray     # [256, D] f32 — rows >= n_coarse are zero pad
    codebooks: np.ndarray  # [M, 256, D/M] f32
    codes: np.ndarray      # [N, 1+M] uint8
    n_coarse: int          # real coarse centroids (<= 256)
    n_items: int
    dim: int
    m: int                 # residual subspaces
    fingerprint: str       # corpus_fingerprint of the quantized vectors

    @property
    def dsub(self) -> int:
        return self.dim // self.m

    @property
    def n_tables(self) -> int:
        """LUT stack height: coarse table + one per subspace."""
        return self.m + 1

    def bytes_per_item(self) -> int:
        """Resident bytes per corpus row (the README memory math)."""
        return self.codes.shape[1]


def _resolve_m(dim: int, requested: Optional[int]) -> int:
    """Subspace count: requested (or ~D/4), rounded DOWN to a divisor of
    D so every subspace has the same width."""
    m = requested if requested and requested > 0 else max(1, dim // 4)
    m = max(1, min(m, dim))
    while dim % m:
        m -= 1
    return m


def pq_build_config(n_items: int, dim: int) -> Tuple[bool, int, int]:
    """(should_build, m, min_items) from the env at train time."""
    mode = os.environ.get("PIO_PQ", "auto").strip().lower() or "auto"
    try:
        min_items = int(os.environ.get("PIO_PQ_MIN_ITEMS",
                                       str(DEFAULT_PQ_MIN_ITEMS)))
    except ValueError:
        min_items = DEFAULT_PQ_MIN_ITEMS
    if mode in ("off", "0", "false", "no"):
        return False, 0, min_items
    if mode not in ("auto", "on", "1", "true", "yes"):
        # A typo'd opt-out must degrade as loudly as any other knob —
        # silently building (and then auto-serving) codes the operator
        # tried to disable is the one direction that must never be
        # quiet.
        logger.warning("PIO_PQ=%r is not one of auto|on|off; treating "
                       "as auto", mode)
    if n_items < min_items:
        # Exact fallback: below the threshold the exact rungs already
        # meet latency and quantization only spends recall (mode=on
        # included; the threshold IS the contract, same as PIO_IVF).
        return False, 0, min_items
    req = None
    raw = os.environ.get("PIO_PQ_M", "").strip()
    if raw:
        try:
            req = int(raw)
        except ValueError:
            logger.warning("PIO_PQ_M=%r is not an integer; using the "
                           "~D/4 default", raw)
    return True, _resolve_m(dim, req), min_items


def _assign_euclidean(data: np.ndarray, centroids: np.ndarray,
                      chunk: int = 262_144) -> np.ndarray:
    """Chunked nearest-centroid assignment (Euclidean).  ``-2·x·cᵀ +
    ‖c‖²`` suffices for the argmin — ‖x‖² is row-constant."""
    c2 = np.einsum("cd,cd->c", centroids, centroids)
    out = np.empty(len(data), dtype=np.int64)
    for s in range(0, len(data), chunk):
        d = data[s:s + chunk] @ (-2.0 * centroids.T) + c2[None, :]
        out[s:s + chunk] = np.argmin(d, axis=1)
    return out


def _lloyd(data: np.ndarray, centroids: np.ndarray,
           iters: int) -> np.ndarray:
    """A few Lloyd iterations refining ``centroids`` over ``data``
    (Euclidean — PQ minimizes reconstruction MSE, which bounds the
    score error ``|q·x − q·x̂| ≤ ‖q‖·‖x − x̂‖`` regardless of vector
    norms; this is why PQ+re-rank is safe where spherical IVF is not).
    Empty clusters keep their previous centroid."""
    cent = centroids.copy()
    for _ in range(iters):
        assign = _assign_euclidean(data, cent)
        sums = np.zeros_like(cent, dtype=np.float64)
        np.add.at(sums, assign, data)
        counts = np.bincount(assign, minlength=len(cent))
        nz = counts > 0
        cent[nz] = (sums[nz] / counts[nz, None]).astype(np.float32)
    return cent


def _coarse_book(vecs: np.ndarray, sample: np.ndarray,
                 ivf: Optional[IVFIndex], iters: int,
                 rng: np.random.Generator) -> np.ndarray:
    """<= 256 coarse centroids, derived from the IVF structure when one
    exists (reuse at nlist <= 256; else the heaviest-list centroids,
    refined on the raw vectors) so PQ residuals sit ON TOP of the
    existing coarse quantizer instead of re-partitioning blind."""
    c0 = min(256, len(vecs))
    if ivf is not None and ivf.dim == vecs.shape[1]:
        if ivf.nlist <= c0:
            return _lloyd(sample, ivf.centroids.astype(np.float32).copy(),
                          max(1, iters // 2))
        heavy = np.argsort(-np.asarray(ivf.list_lengths))[:c0]
        init = ivf.centroids[np.sort(heavy)].astype(np.float32).copy()
        return _lloyd(sample, init, max(1, iters // 2))
    init = sample[rng.choice(len(sample), size=c0, replace=False)].copy()
    return _lloyd(sample, init, iters)


def build_pq(item_vecs: np.ndarray, *, m: Optional[int] = None,
             ivf: Optional[IVFIndex] = None, sample: int = 65_536,
             iters: int = 8, seed: int = 0) -> PQCodebook:
    """Residual PQ over ``item_vecs`` ([N, D] host array).

    Mini-batch flavored like :func:`~predictionio_tpu.retrieval.ivf.
    build_ivf`: the coarse book and the per-subspace codebooks train on
    a deterministic bounded sample; the full corpus is touched only by
    the (chunked, BLAS-shaped) assignment/encode passes, so build cost
    stays bounded at 1e7 scale.
    """
    vecs = np.ascontiguousarray(item_vecs, dtype=np.float32)
    n, d = vecs.shape
    m = _resolve_m(d, m)
    rng = np.random.default_rng(seed)
    sel = rng.choice(n, size=min(sample, n), replace=False) \
        if n > sample else np.arange(n)
    coarse = _coarse_book(vecs, vecs[sel], ivf, iters, rng)
    n_coarse = len(coarse)
    codes = np.zeros((n, 1 + m), dtype=np.uint8)
    codes[:, 0] = _assign_euclidean(vecs, coarse).astype(np.uint8)
    # Residuals of the SAMPLE train the subspace books; the full-corpus
    # residual never materializes — encode passes recompute it chunked.
    res_sample = vecs[sel] - coarse[codes[sel, 0]]
    ds = d // m
    books = np.empty((m, 256, ds), dtype=np.float32)
    for mi in range(m):
        sub = np.ascontiguousarray(res_sample[:, mi * ds:(mi + 1) * ds])
        kk = min(256, len(sub))
        init = sub[rng.choice(len(sub), size=kk, replace=False)].copy()
        book = _lloyd(sub, init, iters)
        if kk < 256:
            book = np.pad(book, ((0, 256 - kk), (0, 0)))
        books[mi] = book
    chunk = 262_144
    for s in range(0, n, chunk):
        res = vecs[s:s + chunk] - coarse[codes[s:s + chunk, 0]]
        for mi in range(m):
            sub = np.ascontiguousarray(res[:, mi * ds:(mi + 1) * ds])
            codes[s:s + chunk, 1 + mi] = \
                _assign_euclidean(sub, books[mi]).astype(np.uint8)
    pad = np.zeros((256, d), dtype=np.float32)
    pad[:n_coarse] = coarse
    pq = PQCodebook(coarse=pad, codebooks=books, codes=codes,
                    n_coarse=n_coarse, n_items=n, dim=d, m=m,
                    fingerprint=corpus_fingerprint(vecs))
    err = float(np.mean(np.linalg.norm(
        vecs[sel] - decode_pq(pq, sel), axis=1)))
    logger.info("built PQ codebook: %d items × %dD → %d B/item "
                "(M=%d, coarse=%d), mean residual |x-x̂| %.4f",
                n, d, pq.bytes_per_item(), m, n_coarse, err)
    return pq


def decode_pq(pq: PQCodebook, ids: Optional[np.ndarray] = None
              ) -> np.ndarray:
    """Reconstructed vectors ``x̂`` for ``ids`` (default: all items) —
    the LUT score of an item is EXACTLY ``q · decode(item)``."""
    codes = pq.codes if ids is None else pq.codes[np.asarray(ids)]
    out = pq.coarse[codes[..., 0].astype(np.int64)].copy()
    ds = pq.dsub
    for mi in range(pq.m):
        out[..., mi * ds:(mi + 1) * ds] += \
            pq.codebooks[mi][codes[..., 1 + mi].astype(np.int64)]
    return out


def lut_tables(pq: PQCodebook, queries: np.ndarray) -> np.ndarray:
    """Per-query asymmetric-distance tables ``[B, 1+M, 256]`` f32.

    Table 0 is the coarse inner products; table ``1+m`` the subspace-m
    residual inner products.  ``Σ_tables lut[t, codes[n, t]]`` ==
    ``q · decode(n)`` identically.
    """
    q = np.ascontiguousarray(queries, dtype=np.float32)
    b, d = q.shape
    ds = pq.dsub
    luts = np.empty((b, pq.n_tables, 256), dtype=np.float32)
    luts[:, 0, :] = q @ pq.coarse.T
    qs = q.reshape(b, pq.m, ds)
    luts[:, 1:, :] = np.einsum("bmd,mcd->bmc", qs, pq.codebooks)
    return luts


def _merge_topr(best_s, best_i, s, i, r):
    """Fold a [B, C] score block into the running [B, r] best set."""
    ms = np.concatenate([best_s, s], axis=1)
    mi = np.concatenate([best_i, i], axis=1)
    if ms.shape[1] > r:
        part = np.argpartition(-ms, r - 1, axis=1)[:, :r]
        return (np.take_along_axis(ms, part, axis=1),
                np.take_along_axis(mi, part, axis=1))
    return ms, mi


def _rerank_host(q: np.ndarray, host_vecs: np.ndarray, cand_s, cand_i,
                 k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Exact re-score of the PQ shortlist; sentinel rows stay sentinel.

    The returned top-k is computed from true fp32 inner products — PQ
    only chose WHICH candidates get the exact treatment.
    """
    b, r = cand_i.shape
    safe = np.maximum(cand_i, 0)
    exact = np.einsum("bd,brd->br", q, host_vecs[safe])
    exact = np.where(cand_s <= _SENTINEL, _NEG_INF, exact)
    kk = min(k, r)
    part = np.argpartition(-exact, kk - 1, axis=1)[:, :kk]
    ps = np.take_along_axis(exact, part, axis=1)
    order = np.argsort(-ps, axis=1, kind="stable")
    top = np.take_along_axis(part, order, axis=1)
    out_s = np.take_along_axis(exact, top, axis=1).astype(np.float32)
    out_i = np.take_along_axis(cand_i, top, axis=1).astype(np.int32)
    out_i = np.where(out_s <= _SENTINEL, -1, out_i)
    if kk < k:
        out_s = np.pad(out_s, ((0, 0), (0, k - kk)),
                       constant_values=_NEG_INF)
        out_i = np.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    return out_s, out_i


def search_pq_host(pq: PQCodebook, host_vecs: np.ndarray,
                   queries: np.ndarray, k: int, rerank: int,
                   chunk: int = 1 << 19
                   ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Numpy full LUT scan + exact re-rank — the pq_flat serving fast
    path.  Returns ([B, k] f32, [B, k] int32, code rows scanned)."""
    q = np.ascontiguousarray(queries, dtype=np.float32)
    b = q.shape[0]
    n = pq.n_items
    luts = lut_tables(pq, q)
    r = min(max(rerank, k), n)
    best_s = np.full((b, 0), _NEG_INF, dtype=np.float32)
    best_i = np.zeros((b, 0), dtype=np.int32)
    for s0 in range(0, n, chunk):
        c = pq.codes[s0:s0 + chunk]
        acc = np.ascontiguousarray(luts[:, 0, :][:, c[:, 0]])
        for mi in range(1, pq.n_tables):
            acc += luts[:, mi, :][:, c[:, mi]]
        ids = np.broadcast_to(
            np.arange(s0, s0 + len(c), dtype=np.int32), acc.shape)
        best_s, best_i = _merge_topr(best_s, best_i, acc, ids, r)
    out_s, out_i = _rerank_host(q, host_vecs, best_s, best_i, k)
    return out_s, out_i, b * n


def search_ivf_pq_host(index: IVFIndex, pq: PQCodebook,
                       host_vecs: np.ndarray, queries: np.ndarray,
                       k: int, nprobe: int, rerank: int
                       ) -> Tuple[np.ndarray, np.ndarray, int]:
    """IVF-pruned LUT scan + exact re-rank: probe ``nprobe`` cells, score
    only their members' CODES (1+M bytes each, not D fp32), shortlist,
    re-rank exactly.  Returns ([B, k], [B, k] int32, rows scanned)."""
    q = np.ascontiguousarray(queries, dtype=np.float32)
    b = q.shape[0]
    nprobe = max(1, min(nprobe, index.nlist))
    luts = lut_tables(pq, q)
    cq = q @ index.centroids.T
    if nprobe < index.nlist:
        probe = np.argpartition(-cq, nprobe - 1, axis=1)[:, :nprobe]
    else:
        probe = np.broadcast_to(np.arange(index.nlist), (b, index.nlist))
    out_s = np.full((b, k), _NEG_INF, dtype=np.float32)
    out_i = np.full((b, k), -1, dtype=np.int32)
    for row in range(b):
        cand = index.lists[probe[row]].ravel()
        cand = cand[cand >= 0]
        if cand.size == 0:
            continue
        c = pq.codes[cand]
        sc = luts[row, 0][c[:, 0]]
        for mi in range(1, pq.n_tables):
            sc = sc + luts[row, mi][c[:, mi]]
        r = min(max(rerank, k), sc.size)
        part = np.argpartition(-sc, r - 1)[:r] if r < sc.size \
            else np.arange(sc.size)
        short = cand[part]
        exact = host_vecs[short] @ q[row]
        kk = min(k, exact.size)
        top = np.argpartition(-exact, kk - 1)[:kk] if kk < exact.size \
            else np.arange(exact.size)
        order = top[np.argsort(-exact[top], kind="stable")]
        out_s[row, :kk] = exact[order]
        out_i[row, :kk] = short[order]
    return out_s, out_i, index.candidates_scanned(probe)


# -- device paths ------------------------------------------------------------


def quantize_int8(vecs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization of a re-rank corpus:
    ``row ≈ q8 · scale`` with ``scale = max|row| / 127`` (zero rows get
    scale 1 so dequantization is exact zeros)."""
    v = np.ascontiguousarray(vecs, dtype=np.float32)
    peak = np.max(np.abs(v), axis=1)
    scale = np.where(peak > 0, peak / 127.0, 1.0).astype(np.float32)
    q8 = np.clip(np.rint(v / scale[:, None]), -127, 127).astype(np.int8)
    return q8, scale


def _device_luts(q, coarse, books):
    """In-program LUT build: [B, 1+M, 256] from the staged codebooks."""
    import jax.numpy as jnp

    b = q.shape[0]
    m, _, ds = books.shape
    lut0 = jnp.einsum("bd,cd->bc", q, coarse,
                      preferred_element_type=jnp.float32)
    qs = q.reshape(b, m, ds)
    lutm = jnp.einsum("bmd,mcd->bmc", qs, books,
                      preferred_element_type=jnp.float32)
    return jnp.concatenate([lut0[:, None, :], lutm], axis=1)


def _rerank_device(q, cand_s, cand_i, rvecs, scales, k: int):
    """Exact re-score of a device shortlist ([B, R] ids) against the
    staged re-rank corpus (f32/bf16, or int8 + per-row scales)."""
    import jax
    import jax.numpy as jnp

    safe = jnp.maximum(cand_i, 0)
    vecs = rvecs[safe].astype(jnp.float32)          # [B, R, D]
    if scales is not None:
        vecs = vecs * scales[safe][..., None]
    exact = jnp.einsum("bd,brd->br", q, vecs,
                       preferred_element_type=jnp.float32)
    exact = jnp.where(cand_s <= jnp.float32(_SENTINEL),
                      jnp.float32(_NEG_INF), exact)
    top_s, pos = jax.lax.top_k(exact, min(k, exact.shape[1]))
    top_i = jnp.take_along_axis(cand_i, pos, axis=1)
    top_i = jnp.where(top_s <= jnp.float32(_SENTINEL), -1, top_i)
    return top_s, top_i


def _pq_flat_impl(q, coarse, books, codes_sn, rvecs, scales, *, k: int,
                  r: int, n_valid: int):
    from predictionio_tpu.ops.pallas_kernels import pq_scan

    luts = _device_luts(q, coarse, books)
    s_r, i_r = pq_scan(luts, codes_sn, r, n_valid=n_valid)
    return _rerank_device(q, s_r, i_r, rvecs, scales, k)


def _ivf_pq_impl(q, cent, lists, coarse, books, codes_sn, rvecs, scales,
                 *, k: int, r: int, nprobe: int):
    import jax
    import jax.numpy as jnp

    luts = _device_luts(q, coarse, books)
    cq = jnp.einsum("bd,cd->bc", q, cent,
                    preferred_element_type=jnp.float32)
    _, probe = jax.lax.top_k(cq, nprobe)                # [B, P]
    cand = lists[probe].reshape(q.shape[0], -1)         # [B, P·L]
    cidx = jnp.maximum(cand, 0)
    cc = jnp.take(codes_sn, cidx, axis=1)               # [S, B, P·L] u8
    s = jnp.take_along_axis(luts[:, 0, :], cc[0].astype(jnp.int32),
                            axis=1)
    for mi in range(1, codes_sn.shape[0]):
        s = s + jnp.take_along_axis(luts[:, mi, :],
                                    cc[mi].astype(jnp.int32), axis=1)
    s = jnp.where(cand < 0, jnp.float32(_NEG_INF), s)
    s_r, pos = jax.lax.top_k(s, min(r, s.shape[1]))
    i_r = jnp.take_along_axis(cand, pos, axis=1)
    top_s, top_i = _rerank_device(q, s_r, i_r, rvecs, scales, k)
    return top_s, top_i, probe


def search_pq_device(pq: PQCodebook, queries, k: int, rerank: int, *,
                     jit_cache: dict, consts: tuple, rerank_consts: tuple
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Jitted full LUT scan (Pallas kernel on TPU, chunked XLA gather
    fallback elsewhere) + exact re-rank.  ``consts`` is the caller's
    pre-staged ``(coarse, codebooks, codes[S, N])`` device triple and
    ``rerank_consts`` its staged ``(vectors, scales|None)`` re-rank
    corpus — generation constants, never re-uploaded per request."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.retrieval.exact import SERVE_CACHE_LOCK

    b = queries.shape[0]
    r = min(max(rerank, k), pq.n_items)
    key = ("pq_flat", b, k, r)
    fn = jit_cache.get(key)
    if fn is None:
        with SERVE_CACHE_LOCK:
            fn = jit_cache.get(key)
            if fn is None:
                fn = jax.jit(partial(_pq_flat_impl, k=k, r=r,
                                     n_valid=pq.n_items))
                jit_cache[key] = fn
    coarse, books, codes_sn = consts
    rvecs, scales = rerank_consts
    s, i = jax.device_get(fn(jnp.asarray(queries, jnp.float32), coarse,
                             books, codes_sn, rvecs, scales))
    return np.asarray(s), np.asarray(i, np.int32), b * pq.n_items


def search_ivf_pq_device(index: IVFIndex, pq: PQCodebook, queries,
                         k: int, nprobe: int, rerank: int, *,
                         jit_cache: dict, ivf_consts: tuple,
                         pq_consts: tuple, rerank_consts: tuple
                         ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Jitted IVF-pruned LUT scan + exact re-rank — one compiled program
    per (B, k, nprobe, rerank), all index/codebook constants pre-staged
    by the caller (same discipline as ``search_ivf_device``)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.retrieval.exact import SERVE_CACHE_LOCK

    b = queries.shape[0]
    nprobe = max(1, min(nprobe, index.nlist))
    r = min(max(rerank, k), nprobe * index.pad_len)
    key = ("ivf_pq", b, k, nprobe, r)
    fn = jit_cache.get(key)
    if fn is None:
        with SERVE_CACHE_LOCK:
            fn = jit_cache.get(key)
            if fn is None:
                fn = jax.jit(partial(_ivf_pq_impl, k=k, r=r,
                                     nprobe=nprobe))
                jit_cache[key] = fn
    cent, lists = ivf_consts
    coarse, books, codes_sn = pq_consts
    rvecs, scales = rerank_consts
    s, i, probe = fn(jnp.asarray(queries, jnp.float32), cent, lists,
                     coarse, books, codes_sn, rvecs, scales)
    s, i, probe = jax.device_get((s, i, probe))
    return (np.asarray(s), np.asarray(i, np.int32),
            index.candidates_scanned(np.asarray(probe)))
