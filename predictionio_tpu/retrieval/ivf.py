"""Train-time IVF coarse index — sublinear candidate selection for MIPS.

The exact rungs (retrieval/exact.py) scan every corpus row per query.
An IVF (inverted-file) index trades a bounded recall loss for a
sublinear scan: k-means centroids partition the corpus at ``pio train``
time; at serve time a query scores only the ``nprobe`` nearest lists.

Design points (ISSUE 8):

- **Padded lists, static shapes.**  Every inverted list is padded to the
  longest list's length with ``-1`` sentinels, so the device search is
  one jitted program per (B, k, nprobe) — no recompile per corpus, no
  ragged gathers.  The host search uses the same arrays.
- **Versioned with the model generation.**  The index carries a
  fingerprint of the exact vector matrix it was built over; the facade
  refuses (and drops) an index whose fingerprint does not match the
  corpus it is being served next to.  Because the index travels INSIDE
  the pickled model wrapper, the staged-reload/rollback path (ISSUE 4/6)
  swaps index+model atomically by construction — the fingerprint check
  is the tripwire that makes a future regression loud instead of a
  silent recall collapse.
- **Exact fallback below a size threshold.**  Brute force over a small
  corpus is faster than any index walk; ``build_ivf`` returns ``None``
  under ``PIO_IVF_MIN_ITEMS`` and the facade never picks the IVF rung
  there.

Knobs: ``PIO_IVF`` (auto|on|off — build policy at train time),
``PIO_IVF_NLIST`` (centroid count, default ~sqrt(N)),
``PIO_IVF_NPROBE`` (lists scanned per query, default ~nlist/8),
``PIO_IVF_MIN_ITEMS`` (exact-fallback threshold, default 50k).

When NOT to use IVF: corpora with heavy vector-norm variance (e.g. raw
ALS factors with popularity-scaled norms) — k-means cells partition by
direction, a high-norm item in an unprobed cell is an unrecoverable
miss.  Normalized embedding corpora (the two-tower tower outputs) are
the design target.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
from functools import partial
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["IVFIndex", "build_ivf", "ivf_build_config", "corpus_fingerprint",
           "search_ivf_host", "search_ivf_device", "DEFAULT_MIN_ITEMS"]

DEFAULT_MIN_ITEMS = 50_000
_NEG_INF = np.float32(-3.4e38)


def corpus_fingerprint(vecs: np.ndarray) -> str:
    """Stable identity of a vector matrix (shape + content digest).

    Hashed over the contiguous f32 bytes so the SAME vectors loaded from
    a pickle round-trip fingerprint identically; ~100 ms at the 1e6×64
    scale, paid once per index build and once per model load.
    """
    a = np.ascontiguousarray(vecs, dtype=np.float32)
    h = hashlib.sha1()
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class IVFIndex:
    """K-means centroids + padded inverted lists over one item corpus.

    Pickled inside the model wrapper it indexes — model and index are ONE
    serialized artifact, so a generation swap can never mix them.
    """

    centroids: np.ndarray      # [C, D] f32
    lists: np.ndarray          # [C, L] int32, -1 = padding
    list_lengths: np.ndarray   # [C] int32 — true (unpadded) lengths
    n_items: int
    dim: int
    nlist: int
    pad_len: int               # L
    fingerprint: str           # corpus_fingerprint of the indexed vectors

    def default_nprobe(self) -> int:
        """Serve-time probe width: env override, else ~nlist/8 (≥ 1) —
        the default that holds recall@10 ≥ 0.95 on clustered corpora
        while scanning well under a quarter of the candidates."""
        raw = os.environ.get("PIO_IVF_NPROBE", "").strip()
        if raw:
            try:
                return max(1, min(int(raw), self.nlist))
            except ValueError:
                pass
        return max(1, -(-self.nlist // 8))

    def candidates_scanned(self, probe_ids: np.ndarray) -> int:
        """True candidate rows scored for a [B, P] probe assignment."""
        return int(self.list_lengths[probe_ids].sum())

    def min_nprobe_for(self, k: int) -> int:
        """Smallest probe width that guarantees ≥ k REAL candidates for
        any query — worst case, it probes the nprobe SHORTEST lists, so
        the bound must use true list lengths.  ``nprobe · pad_len``
        overcounts skewed clusters (one giant list sets the pad while
        typical lists hold a handful of items) and silently returns
        fewer than k results."""
        cum = getattr(self, "_worst_case_cum", None)
        if cum is None:
            cum = np.cumsum(np.sort(np.asarray(self.list_lengths,
                                               dtype=np.int64)))
            self._worst_case_cum = cum
        if cum[-1] < k:
            return self.nlist
        return int(np.searchsorted(cum, k)) + 1


def ivf_build_config(n_items: int) -> Tuple[bool, int, int]:
    """(should_build, nlist, min_items) from the env at train time."""
    mode = os.environ.get("PIO_IVF", "auto").strip().lower() or "auto"
    try:
        min_items = int(os.environ.get("PIO_IVF_MIN_ITEMS",
                                       str(DEFAULT_MIN_ITEMS)))
    except ValueError:
        min_items = DEFAULT_MIN_ITEMS
    if mode in ("off", "0", "false", "no"):
        return False, 0, min_items
    if n_items < min_items:
        # Exact fallback: below the threshold brute force wins — never
        # build (mode=on included; the threshold IS the contract).
        return False, 0, min_items
    raw = os.environ.get("PIO_IVF_NLIST", "").strip()
    nlist = 0
    if raw:
        try:
            nlist = max(1, min(int(raw), n_items))
        except ValueError:
            logger.warning("PIO_IVF_NLIST=%r is not an integer; using "
                           "the ~sqrt(N) default", raw)
    if not nlist:
        nlist = max(1, min(int(round(float(n_items) ** 0.5)), n_items))
    return True, nlist, min_items


def build_ivf(item_vecs: np.ndarray, *, nlist: Optional[int] = None,
              iters: int = 6, sample: int = 65_536, seed: int = 0,
              force: bool = False) -> Optional[IVFIndex]:
    """Spherical k-means index over ``item_vecs`` ([N, D] host array).

    Mini-batch flavored: centroids train on a deterministic sample (the
    full assignment pass is the only full-corpus scan), so build cost is
    bounded at ML-25M scale.  Returns ``None`` when the env policy says
    exact-only (``force=True`` skips the policy for tests/benches, not
    the math).
    """
    vecs = np.ascontiguousarray(item_vecs, dtype=np.float32)
    n, d = vecs.shape
    if force:
        c = nlist or max(1, min(int(round(float(n) ** 0.5)), n))
    else:
        build, c, _ = ivf_build_config(n)
        if not build:
            return None
        c = nlist or c
    c = max(1, min(c, n))
    rng = np.random.default_rng(seed)
    # Direction-only clustering: normalize a working copy so cells
    # partition the sphere (MIPS over normalized corpora ≡ cosine).
    norms = np.linalg.norm(vecs, axis=1, keepdims=True)
    unit = vecs / np.where(norms < 1e-9, 1.0, norms)
    train = unit[rng.choice(n, size=min(sample, n), replace=False)] \
        if n > sample else unit
    centroids = train[rng.choice(len(train), size=c, replace=False)].copy()
    for _ in range(iters):
        # [S, C] cosine scores; argmax assignment; mean + renormalize.
        assign = np.argmax(train @ centroids.T, axis=1)
        for ci in range(c):
            members = train[assign == ci]
            if len(members):
                centroids[ci] = members.mean(axis=0)
        cn = np.linalg.norm(centroids, axis=1, keepdims=True)
        centroids = centroids / np.where(cn < 1e-9, 1.0, cn)
    # Full assignment pass, chunked so the [chunk, C] block stays small.
    assign = np.empty(n, dtype=np.int64)
    step = max(1, 4_194_304 // max(c, 1))
    for s in range(0, n, step):
        assign[s:s + step] = np.argmax(unit[s:s + step] @ centroids.T, axis=1)
    counts = np.bincount(assign, minlength=c)
    pad_len = max(1, int(counts.max()))
    lists = np.full((c, pad_len), -1, dtype=np.int32)
    fill = np.zeros(c, dtype=np.int64)
    order = np.argsort(assign, kind="stable")
    for idx in order:
        ci = assign[idx]
        lists[ci, fill[ci]] = idx
        fill[ci] += 1
    index = IVFIndex(
        centroids=centroids.astype(np.float32),
        lists=lists,
        list_lengths=counts.astype(np.int32),
        n_items=n, dim=d, nlist=c, pad_len=pad_len,
        fingerprint=corpus_fingerprint(vecs),
    )
    logger.info("built IVF index: %d items → %d lists (pad_len=%d, "
                "mean len %.1f)", n, c, pad_len, counts.mean())
    return index


def search_ivf_host(index: IVFIndex, item_vecs: np.ndarray,
                    queries: np.ndarray, k: int, nprobe: int
                    ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Numpy IVF search — the serving fast path for small batches.

    Returns ([B, k] f32 scores, [B, k] int32 ids, candidates scanned).
    Rows with fewer than k reachable candidates pad with NEG_INF/-1.
    """
    q = np.ascontiguousarray(queries, dtype=np.float32)
    b = q.shape[0]
    nprobe = max(1, min(nprobe, index.nlist))
    cq = q @ index.centroids.T                         # [B, C]
    if nprobe < index.nlist:
        probe = np.argpartition(-cq, nprobe - 1, axis=1)[:, :nprobe]
    else:
        probe = np.broadcast_to(np.arange(index.nlist), (b, index.nlist))
    out_s = np.full((b, k), _NEG_INF, dtype=np.float32)
    out_i = np.full((b, k), -1, dtype=np.int32)
    for row in range(b):
        cand = index.lists[probe[row]].ravel()
        cand = cand[cand >= 0]
        if cand.size == 0:
            continue
        sc = item_vecs[cand] @ q[row]
        kk = min(k, sc.size)
        part = np.argpartition(-sc, kk - 1)[:kk] if kk < sc.size \
            else np.arange(sc.size)
        order = part[np.argsort(-sc[part], kind="stable")]
        out_s[row, :kk] = sc[order]
        out_i[row, :kk] = cand[order]
    return out_s, out_i, index.candidates_scanned(probe)


def _device_search_impl(queries, centroids, lists, items, *, k: int,
                        nprobe: int):
    import jax
    import jax.numpy as jnp

    cq = jnp.einsum("bd,cd->bc", queries, centroids,
                    preferred_element_type=jnp.float32)
    _, probe = jax.lax.top_k(cq, nprobe)               # [B, P]
    cand = lists[probe].reshape(queries.shape[0], -1)  # [B, P·L]
    vecs = items[jnp.maximum(cand, 0)]                 # [B, P·L, D]
    sc = jnp.einsum("bd,bnd->bn", queries, vecs,
                    preferred_element_type=jnp.float32)
    sc = jnp.where(cand < 0, jnp.float32(_NEG_INF), sc)
    top_s, pos = jax.lax.top_k(sc, k)
    return top_s, jnp.take_along_axis(cand, pos, axis=1), probe


def search_ivf_device(index: IVFIndex, items_dev, queries,
                      k: int, nprobe: int, *, jit_cache: dict,
                      consts: Optional[tuple] = None
                      ) -> Tuple["np.ndarray", "np.ndarray", int]:
    """Jitted static-shape IVF search for larger batches.

    One compiled program per (B, k, nprobe) — the padded [C, L] lists
    make every gather static.  ``jit_cache`` is the caller's per-corpus
    compiled-program cache (keyed here, owned there so a model reload
    drops it with the corpus).  ``consts`` is the caller's pre-staged
    ``(centroids, lists)`` device pair — generation constants that must
    not be re-uploaded per request on the serving hot path.
    """
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.retrieval.exact import SERVE_CACHE_LOCK

    b = queries.shape[0]
    nprobe = max(1, min(nprobe, index.nlist))
    key = ("ivf", b, k, nprobe)
    fn = jit_cache.get(key)
    if fn is None:
        # Same cold-build discipline as the exact rungs: a burst of
        # concurrent first requests must trace ONE program, not one each.
        with SERVE_CACHE_LOCK:
            fn = jit_cache.get(key)
            if fn is None:
                fn = jax.jit(partial(_device_search_impl, k=k,
                                     nprobe=nprobe))
                jit_cache[key] = fn
    cent, lists = consts if consts is not None else (
        jnp.asarray(index.centroids), jnp.asarray(index.lists))
    top_s, top_i, probe = fn(jnp.asarray(queries, jnp.float32),
                             cent, lists, items_dev)
    top_s, top_i, probe = jax.device_get((top_s, top_i, probe))
    return (np.asarray(top_s), np.asarray(top_i, np.int32),
            index.candidates_scanned(np.asarray(probe)))
