"""Retrieval subsystem — THE way serving reaches an item corpus.

ISSUE 8: every template's predict path used to hand-roll its own
host-vs-device-vs-chunked-vs-sharded branching over ``ops.topk``; serve
latency and HBM grew linearly with catalog size on ONE device.  This
facade puts three rungs behind one call:

1. **Exact** (``retrieval/exact.py``) — host numpy for small work,
   single-dispatch device, bounded-memory chunked scan (fused Pallas
   score+top-K kernel on TPU), and mesh-sharded scoring with an
   O(k·shards·B) cross-device merge for corpora row-sharded at
   model-load time.
2. **IVF** (``retrieval/ivf.py``) — train-time k-means coarse index,
   sublinear candidate scan, versioned with the model generation via a
   corpus fingerprint (an index that does not match the vectors it is
   served next to is dropped loudly, never silently mis-served).
3. **PQ** (``retrieval/pq.py``, ISSUE 13) — train-time residual product
   quantization: the resident corpus shrinks to 1+M bytes/item, serving
   LUT-scores packed codes (``ivf_pq`` prunes by cell first; ``pq_flat``
   scans every code row) and re-ranks a ``PIO_PQ_RERANK`` shortlist
   against exact embeddings so recall never rides quantization error.
   Codebooks carry the same fingerprint tripwire as the IVF index.
4. **Fused kernels** (``ops/pallas_kernels.fused_topk`` /
   ``pq_scan``) — ride inside the chunked and PQ rungs where the
   backend supports them.

Templates hold ONE :class:`Retriever` per loaded model (via
:func:`cached_retriever` — weak-keyed, so it dies with the generation)
and call :meth:`Retriever.topk`.  ``tools/lint_retrieval.py`` pins the
invariant: no template or server handler may call ``ops.topk``
primitives directly.

Routing knobs (all read per request, so ops can retune a live server):

- ``PIO_RETRIEVAL_RUNG`` — auto|host|device|chunked|sharded|ivf|ivf_pq|
  pq_flat (force)
- ``PIO_SERVE_HOST_MACS`` — host fast path when B·N·D is at or below
  this (default 2e8): one device dispatch round-trip costs more than
  that many host MACs, which is exactly the lone-client B=1 case
- ``PIO_SERVE_CHUNK_ABOVE`` — chunked scan above this many items
- ``PIO_SERVE_SHARD_ABOVE`` — shard-at-load threshold (see
  :meth:`Retriever.maybe_shard`)
- ``PIO_IVF_NPROBE`` — IVF lists probed per query
- ``PIO_PQ_RERANK`` — exact-re-rank shortlist size (default 4·k)
- ``PIO_CORPUS_DTYPE`` — f32|bf16|int8 staged re-rank corpus

Observability: ``pio_retrieval_requests_total{rung}``,
``pio_retrieval_candidates_total{rung}`` (rows actually scored),
``pio_retrieval_ms{rung}``, and a ``retrieval`` span (rung, k, nprobe,
candidates, batch) in the live request's trace tree.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import weakref
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from predictionio_tpu.obs import get_registry, span
from predictionio_tpu.obs.waterfall import record_stage
from predictionio_tpu.retrieval import exact as _exact
from predictionio_tpu.retrieval.ivf import (
    IVFIndex,
    build_ivf,
    corpus_fingerprint,
    ivf_build_config,
    search_ivf_device,
    search_ivf_host,
)
from predictionio_tpu.retrieval.pq import (
    PQCodebook,
    build_pq,
    pq_build_config,
    quantize_int8,
    search_ivf_pq_device,
    search_ivf_pq_host,
    search_pq_device,
    search_pq_host,
)

logger = logging.getLogger(__name__)

__all__ = ["Retriever", "Plan", "cached_retriever", "arm_on_create",
           "iter_hits",
           "build_train_index", "build_train_pq", "IVFIndex",
           "PQCodebook", "build_ivf", "build_pq",
           "corpus_fingerprint", "K_MENU"]

# Compiled-program menu (SURVEY §7): K pads up so the serving frontend's
# varying ``num`` values hit a handful of XLA programs, not one each.
K_MENU = (1, 10, 100, 1000)
_NEG_SENTINEL = -1e37  # scores at/below this are padding, never results

RUNGS = ("host", "device", "chunked", "sharded", "ivf", "ivf_pq",
         "pq_flat")
# Rungs that honor a per-request exclude mask (everything else pins the
# query to an exact rung — a blacklisted id must never be returned).
EXCLUDE_RUNGS = ("host", "device", "chunked")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)) or default)
    except ValueError:
        return default


def menu_k(num: int, n_items: int) -> int:
    return min(n_items, next((m for m in K_MENU if m >= num), num))


@dataclasses.dataclass
class Plan:
    """One routing decision — exposed for tests and the trace span."""

    rung: str
    k: int
    nprobe: int = 0
    rerank: int = 0  # PQ rungs: exact-re-scored shortlist size


class Retriever:
    """Facade over the retrieval rungs for ONE item corpus.

    ``item_vecs`` may be a host numpy array or a jax array (possibly
    row-sharded over a mesh; possibly carrying padding rows past
    ``n_items``).  The retriever lazily stages whatever copies its rungs
    need (host copy, device copy, sharded copy) — at most one of each,
    built under a process-wide lock.
    """

    def __init__(self, item_vecs, *, n_items: Optional[int] = None,
                 ivf: Optional[IVFIndex] = None,
                 pq: Optional[PQCodebook] = None, name: str = "default",
                 host_fn=None):
        self._vecs = item_vecs
        self.n_items = int(n_items if n_items is not None
                           else item_vecs.shape[0])
        self.dim = int(item_vecs.shape[1])
        self.name = name
        self._host_fn = host_fn
        self._host: Optional[np.ndarray] = None
        self._dev = None
        self._jit: Dict = {}
        # RLock: ivf_index()/pq_codebook() validate fingerprints under
        # the lock and that validation stages host_vecs(), which locks
        # again.
        self._lock = threading.RLock()
        self._ivf_raw = ivf
        self._ivf: Optional[IVFIndex] = None
        self._ivf_checked = False
        self._ivf_dev = None
        self._pq_raw = pq
        self._pq: Optional[PQCodebook] = None
        self._pq_checked = False
        self._pq_dev = None
        self._rerank_dev: Dict = {}
        self._fp: Optional[str] = None
        # Recall capture hook (ISSUE 16): armed per generation by
        # ``obs.recall.RecallMonitor`` — called after approximate-rung
        # answers with (retriever, plan, queries, ids, scanned) so
        # sampled requests can be exactly re-ranked off-thread.  None
        # (the default, and whenever PIO_RECALL=off) costs one attribute
        # read per topk.
        self.recall_hook = None
        reg = get_registry()
        self._m_requests = reg.counter(
            "pio_retrieval_requests_total",
            "Corpus retrievals by rung.", ("rung", "corpus"))
        self._m_candidates = reg.counter(
            "pio_retrieval_candidates_total",
            "Candidate item rows actually scored.", ("rung", "corpus"))
        self._m_latency = reg.histogram(
            "pio_retrieval_ms", "Retrieval latency per rung.", ("rung",))
        self._m_ivf_rejected = reg.counter(
            "pio_retrieval_ivf_rejected_total",
            "IVF indexes dropped for a fingerprint mismatch with the "
            "served corpus.", ("corpus",))
        self._m_pq_rejected = reg.counter(
            "pio_retrieval_pq_rejected_total",
            "PQ codebooks dropped for a fingerprint mismatch with the "
            "served corpus.", ("corpus",))

    # -- corpus staging -----------------------------------------------------

    @property
    def vecs(self):
        """The corpus array currently backing retrieval (numpy, device,
        or mesh-sharded — whatever :meth:`maybe_shard` last staged).
        Callers that keep their own reference (the model wrapper) sync
        from here after a re-shard so the pre-shard copy can be freed."""
        return self._vecs

    @property
    def sharded(self) -> bool:
        sh = getattr(self._vecs, "sharding", None)
        try:
            from jax.sharding import NamedSharding
        except Exception:  # pragma: no cover - jax always present in prod
            return False
        return (isinstance(sh, NamedSharding) and bool(sh.spec)
                and sh.spec[0] is not None
                and self._vecs.shape[0] % sh.mesh.shape[sh.spec[0]] == 0)

    def host_vecs(self) -> np.ndarray:
        """[n_items, D] numpy copy (trimmed of padding rows)."""
        if self._host is None:
            with self._lock:
                if self._host is None:
                    if self._host_fn is not None:
                        self._host = np.asarray(self._host_fn(),
                                                dtype=np.float32)
                    else:
                        import jax

                        self._host = np.asarray(
                            jax.device_get(self._vecs),
                            dtype=np.float32)[: self.n_items]
        return self._host

    def device_vecs(self):
        """Unsharded device copy — staged ONCE, reused across requests
        (the old per-request ``jnp.asarray(model.item_vecs)`` uploaded
        the whole corpus on every predict)."""
        if self.sharded:
            return self._vecs
        if self._dev is None:
            with _exact.SERVE_CACHE_LOCK:
                if self._dev is None:
                    import jax.numpy as jnp

                    self._dev = jnp.asarray(self._vecs, jnp.float32)
        return self._dev

    def maybe_shard(self, mesh, *, axis: Optional[str] = None) -> bool:
        """Row-shard the corpus over ``mesh`` at model-load time.

        The post_load hook's contract (SURVEY §3.2 re-parallelization):
        above ``PIO_SERVE_SHARD_ABOVE`` items the corpus is padded
        HOST-side (a device-side pad would stage the full corpus on one
        chip first — OOM at exactly the scale this targets) and
        device_put shard-by-shard; predict then routes through the
        sharded rung.  Returns True when the corpus was (re)sharded.
        """
        if mesh is None:
            return False
        from predictionio_tpu.parallel.mesh import AXIS_DATA, put_sharded

        axis = axis or AXIS_DATA
        if axis not in mesh.shape:
            return False
        if self.n_items <= _env_int("PIO_SERVE_SHARD_ABOVE", 1_000_000):
            return False
        from jax.sharding import NamedSharding, PartitionSpec as P

        host = self.host_vecs()
        d = mesh.shape[axis]
        pad = (-host.shape[0]) % d
        vecs = np.pad(host, ((0, pad), (0, 0))) if pad else host
        self._vecs = put_sharded(vecs, mesh, NamedSharding(mesh, P(axis)))
        self._dev = None
        self._jit = {}
        # The f32 re-rank staging may hold the pre-shard unsharded
        # device copy — drop it so the post-shard resolution (host-copy
        # based) applies and the old whole-corpus buffer can free.
        self._rerank_dev = {}
        return True

    # -- IVF / PQ lifecycle --------------------------------------------------

    def _corpus_fp(self) -> str:
        """SHA-1 of the served corpus — computed once, shared by the IVF
        and PQ tripwires (each validation used to re-hash the matrix)."""
        if self._fp is None:
            with self._lock:
                if self._fp is None:
                    self._fp = corpus_fingerprint(self.host_vecs())
        return self._fp

    def ivf_index(self) -> Optional[IVFIndex]:
        """The generation's IVF index, fingerprint-validated ONCE against
        the corpus actually being served.  A mismatch (index from another
        generation next to these vectors) drops the index and counts —
        exact serving continues, recall never silently collapses."""
        if self._ivf_checked:
            return self._ivf
        with self._lock:
            if self._ivf_checked:
                return self._ivf
            idx = self._ivf_raw
            if idx is not None:
                if (idx.n_items != self.n_items or idx.dim != self.dim
                        or idx.fingerprint != self._corpus_fp()):
                    logger.error(
                        "IVF index fingerprint mismatch for corpus %r "
                        "(index n=%d/d=%d vs corpus n=%d/d=%d) — dropping "
                        "the index; serving stays exact", self.name,
                        idx.n_items, idx.dim, self.n_items, self.dim)
                    self._m_ivf_rejected.inc(corpus=self.name)
                    idx = None
            self._ivf = idx
            self._ivf_checked = True
        return self._ivf

    def ivf_device_arrays(self):
        """Centroids ``[C, D]`` + padded lists ``[C, L]`` staged on
        device ONCE per generation — index constants; re-uploading them
        per request is the same trap the staged corpus copy closed."""
        if self._ivf_dev is None:
            with _exact.SERVE_CACHE_LOCK:
                if self._ivf_dev is None:
                    import jax.numpy as jnp

                    idx = self.ivf_index()
                    self._ivf_dev = (jnp.asarray(idx.centroids),
                                     jnp.asarray(idx.lists))
        return self._ivf_dev

    def pq_codebook(self) -> Optional[PQCodebook]:
        """The generation's PQ codebook, fingerprint-validated ONCE
        against the served corpus.  A mismatched codebook (codes from
        another generation next to these vectors) is dropped loudly —
        exact serving continues, results are never silently wrong."""
        if self._pq_checked:
            return self._pq
        with self._lock:
            if self._pq_checked:
                return self._pq
            pq = self._pq_raw
            if pq is not None:
                if (pq.n_items != self.n_items or pq.dim != self.dim
                        or pq.fingerprint != self._corpus_fp()):
                    logger.error(
                        "PQ codebook fingerprint mismatch for corpus %r "
                        "(codes n=%d/d=%d vs corpus n=%d/d=%d) — "
                        "dropping the codebook; serving stays exact",
                        self.name, pq.n_items, pq.dim, self.n_items,
                        self.dim)
                    self._m_pq_rejected.inc(corpus=self.name)
                    pq = None
            self._pq = pq
            self._pq_checked = True
        return self._pq

    def pq_device_arrays(self):
        """Coarse book [256, D] + codebooks [M, 256, D/M] + the packed
        code matrix TRANSPOSED to scan layout [1+M, N] uint8 — staged on
        device ONCE per generation (the code matrix IS the resident
        quantized corpus; re-uploading it per request would defeat the
        whole memory story)."""
        if self._pq_dev is None:
            with _exact.SERVE_CACHE_LOCK:
                if self._pq_dev is None:
                    import jax.numpy as jnp

                    pq = self.pq_codebook()
                    self._pq_dev = (
                        jnp.asarray(pq.coarse),
                        jnp.asarray(pq.codebooks),
                        jnp.asarray(np.ascontiguousarray(pq.codes.T)))
        return self._pq_dev

    def rerank_arrays(self):
        """The staged exact re-rank corpus under ``PIO_CORPUS_DTYPE``:
        ``(vectors, None)`` for f32/bf16 or ``(int8, row_scales)`` —
        per-dtype copies staged once so a live retune of the env never
        re-uploads on the hot path.  f32 reuses the exact rungs' staged
        device copy outright."""
        raw = os.environ.get("PIO_CORPUS_DTYPE", "f32").strip().lower() \
            or "f32"
        dtype = {"f32": "f32", "float32": "f32", "bf16": "bf16",
                 "bfloat16": "bf16", "int8": "int8"}.get(raw)
        if dtype is None:
            logger.warning("PIO_CORPUS_DTYPE=%r is not one of "
                           "f32|bf16|int8; staging f32", raw)
            dtype = "f32"
        staged = self._rerank_dev.get(dtype)
        if staged is not None:
            return staged
        if dtype == "f32" and self.n_items * self.dim * 4 > 1 << 28:
            # The default keeps the re-rank corpus exact, but above
            # ~256 MB that re-stages the very fp32 residency PQ exists
            # to remove — say so ONCE, with the fix, instead of letting
            # the first request OOM a chip that only fits the codes.
            logger.warning(
                "PQ re-rank corpus %r stages %.0f MB of fp32 on device "
                "(PIO_CORPUS_DTYPE=f32 default); set "
                "PIO_CORPUS_DTYPE=bf16 or int8 to shrink the resident "
                "re-rank copy 2-4x", self.name,
                self.n_items * self.dim * 4 / 2 ** 20)
        if dtype == "f32" and not self.sharded:
            # device_vecs() takes SERVE_CACHE_LOCK itself — stage it
            # BEFORE acquiring the lock here (non-reentrant).
            staged = (self.device_vecs(), None)
            self._rerank_dev[dtype] = staged
            return staged
        with _exact.SERVE_CACHE_LOCK:
            staged = self._rerank_dev.get(dtype)
            if staged is None:
                import jax.numpy as jnp

                if dtype == "f32":
                    # A mesh-sharded corpus can't feed the PQ gather
                    # directly; re-rank gets its own unsharded copy
                    # (pick bf16/int8 at this scale).
                    staged = (jnp.asarray(self.host_vecs()), None)
                elif dtype == "bf16":
                    staged = (jnp.asarray(self.host_vecs(),
                                          jnp.bfloat16), None)
                else:
                    q8, sc = quantize_int8(self.host_vecs())
                    staged = (jnp.asarray(q8), jnp.asarray(sc))
                self._rerank_dev[dtype] = staged
        return staged

    # -- routing ------------------------------------------------------------

    def plan(self, b: int, num: int, *, has_exclude: bool = False) -> Plan:
        k = menu_k(num, self.n_items)
        forced = os.environ.get("PIO_RETRIEVAL_RUNG", "auto").strip().lower()
        if forced not in RUNGS and forced not in ("", "auto"):
            # An unrecognized forcing must degrade as loudly as an
            # impossible one — a typo'd bench must not silently measure
            # auto routing.
            logger.warning("PIO_RETRIEVAL_RUNG=%r is not one of %s; "
                           "auto routing", forced, ("auto",) + RUNGS)
        if forced in RUNGS:
            if has_exclude and forced not in EXCLUDE_RUNGS:
                # The sharded/IVF executors take no per-request mask —
                # honoring the exclusion beats honoring the forcing (a
                # blacklisted item must never be returned).
                logger.warning(
                    "PIO_RETRIEVAL_RUNG=%s cannot honor a per-request "
                    "exclude mask for corpus %r; serving exact", forced,
                    self.name)
                forced = "auto"
            if forced == "sharded" and not self.sharded:
                logger.warning("PIO_RETRIEVAL_RUNG=sharded but corpus %r "
                               "is not mesh-sharded; serving exact-device",
                               self.name)
                forced = "device"
            if forced == "ivf" and self.ivf_index() is None:
                logger.warning("PIO_RETRIEVAL_RUNG=ivf but corpus %r has "
                               "no valid index; serving exact", self.name)
                forced = "auto"
            if forced in ("ivf_pq", "pq_flat") \
                    and self.pq_codebook() is None:
                logger.warning("PIO_RETRIEVAL_RUNG=%s but corpus %r has "
                               "no valid PQ codebook; serving exact",
                               forced, self.name)
                forced = "auto"
            if forced == "ivf_pq" and self.ivf_index() is None:
                logger.warning("PIO_RETRIEVAL_RUNG=ivf_pq but corpus %r "
                               "has no valid IVF index; serving pq_flat",
                               self.name)
                forced = "pq_flat"
            if forced in RUNGS:
                return self._finish_plan(forced, b, k)
        work = b * self.n_items * self.dim
        host_macs = _env_int("PIO_SERVE_HOST_MACS", 2 * 10 ** 8)
        if has_exclude:
            # Per-request [B, N] masks ride the exact rungs only (an
            # excluded id must never cost recall the way an unprobed
            # IVF cell or a quantized shortlist would); past the chunk
            # threshold the mask rides the scan so score memory stays
            # bounded at [B, chunk].
            if work <= host_macs:
                return self._finish_plan("host", b, k)
            if self.n_items > _env_int("PIO_SERVE_CHUNK_ABOVE", 2_000_000):
                return self._finish_plan("chunked", b, k)
            return self._finish_plan("device", b, k)
        if self.pq_codebook() is not None:
            # Quantized serving when the generation carries codes:
            # IVF-pruned when it also carries a valid index, full LUT
            # scan otherwise (the norm-variant / opted-out-of-IVF
            # shape) — the exact re-rank holds recall either way.
            if self.ivf_index() is not None:
                return self._finish_plan("ivf_pq", b, k)
            return self._finish_plan("pq_flat", b, k)
        if self.ivf_index() is not None:
            return self._finish_plan("ivf", b, k)
        if work <= host_macs:
            return self._finish_plan("host", b, k)
        if self.sharded:
            return self._finish_plan("sharded", b, k)
        if self.n_items > _env_int("PIO_SERVE_CHUNK_ABOVE", 2_000_000):
            return self._finish_plan("chunked", b, k)
        return self._finish_plan("device", b, k)

    def _rerank_count(self, k: int) -> int:
        """PQ shortlist size: ``PIO_PQ_RERANK`` (absolute), default 4·k —
        clamped to [k, n_items].  The top-k the caller sees is always
        computed from exact scores over this many candidates."""
        raw = os.environ.get("PIO_PQ_RERANK", "").strip()
        r = 0
        if raw:
            try:
                r = int(raw)
            except ValueError:
                logger.warning("PIO_PQ_RERANK=%r is not an integer; "
                               "using the 4·k default", raw)
        if r <= 0:
            r = 4 * k
        return min(self.n_items, max(r, k))

    def _finish_plan(self, rung: str, b: int, k: int) -> Plan:
        if rung == "pq_flat":
            return Plan(rung=rung, k=k, rerank=self._rerank_count(k))
        if rung not in ("ivf", "ivf_pq"):
            return Plan(rung=rung, k=k)
        idx = self.ivf_index()
        # Static-shape guard: the probed lists must reach k (or, with a
        # PQ shortlist, rerank) REAL candidates even for the query
        # landing on the shortest lists.
        reach = self._rerank_count(k) if rung == "ivf_pq" else k
        nprobe = min(idx.nlist,
                     max(idx.default_nprobe(), idx.min_nprobe_for(reach)))
        if rung == "ivf_pq":
            return Plan(rung=rung, k=k, nprobe=nprobe, rerank=reach)
        return Plan(rung="ivf", k=k, nprobe=nprobe)

    # -- the one entry point ------------------------------------------------

    def topk(self, queries: np.ndarray, num: int, *,
             exclude: Optional[np.ndarray] = None
             ) -> Tuple[np.ndarray, np.ndarray, Dict[str, Any]]:
        """Top-k over the corpus for query VECTORS ``[B, D]``.

        Returns ``([B, k] scores, [B, k] int32 ids, info)`` with
        ``k = menu_k(num) ≤ n_items`` — callers slice ``[:num]`` per row
        (:func:`iter_hits` skips padding sentinels).  ``exclude`` is an
        optional ``[B, n_items]`` bool mask (True = never return).
        """
        q = np.ascontiguousarray(queries, dtype=np.float32)
        b = q.shape[0]
        p = self.plan(b, num, has_exclude=exclude is not None)
        t0 = time.perf_counter()
        with span("retrieval", corpus=self.name, rung=p.rung, batch=b,
                  k=p.k) as sp:
            scores, ids, scanned = self._execute(q, p, exclude)
            if p.nprobe:
                sp.set(nprobe=p.nprobe)
            if p.rerank:
                sp.set(rerank=p.rerank)
            sp.set(candidates=scanned)
        ms = (time.perf_counter() - t0) * 1e3
        self._m_requests.inc(rung=p.rung, corpus=self.name)
        self._m_candidates.inc(scanned, rung=p.rung, corpus=self.name)
        self._m_latency.observe(ms, rung=p.rung)
        # Waterfall hand-off (ISSUE 9): the serving batcher routes this
        # into the per-dispatch sink and fans it out to every member of
        # the cohort as the rung-tagged "retrieval" stage (⊂ dispatch).
        record_stage("retrieval", ms, rung=p.rung,
                     retrievalCandidates=scanned)
        hook = self.recall_hook
        if hook is not None and p.rung in ("ivf", "ivf_pq", "pq_flat"):
            # Sampled recall capture (ISSUE 16) — the hook does its own
            # shared-draw sampling and bounded enqueue; it must never be
            # able to fail a serving answer.
            try:
                hook(self, p, q, ids, scanned)
            except Exception:
                logger.debug("recall capture failed", exc_info=True)
        info = {"rung": p.rung, "k": p.k, "nprobe": p.nprobe,
                "rerank": p.rerank, "candidates": scanned, "ms": ms}
        return scores, ids, info

    def _execute(self, q: np.ndarray, p: Plan,
                 exclude: Optional[np.ndarray]):
        b = q.shape[0]
        if p.rung == "host":
            s, i = _exact.exact_host(q, self.host_vecs(), p.k,
                                     exclude=exclude)
            return s, i, b * self.n_items
        if p.rung in ("pq_flat", "ivf_pq"):
            return self._execute_pq(q, p)
        if p.rung == "ivf":
            idx = self.ivf_index()
            # The sub-linear scan keeps the same host-vs-device economics
            # as the exact rungs, judged on the rows actually scored.
            est = b * p.nprobe * idx.pad_len * self.dim
            if est <= _env_int("PIO_SERVE_HOST_MACS", 2 * 10 ** 8):
                return search_ivf_host(idx, self.host_vecs(), q, p.k,
                                       p.nprobe)
            qp = _pow2_pad(q)
            s, i, scanned = search_ivf_device(
                idx, self.device_vecs(), qp, p.k, p.nprobe,
                jit_cache=self._jit, consts=self.ivf_device_arrays())
            # scanned counts the padded batch's probes; rescale to real.
            return s[:b], i[:b], int(scanned * b / max(len(qp), 1))
        qp = _pow2_pad(q)
        if exclude is not None and len(qp) > b:
            # The pow2 pad added all-zero query rows; give them
            # all-False mask rows so shapes stay aligned.
            exclude = np.concatenate(
                [exclude, np.zeros((len(qp) - b, exclude.shape[1]),
                                   dtype=bool)])
        if p.rung == "sharded":
            s, i = _exact.exact_sharded(qp, self._vecs, self.n_items, p.k,
                                        jit_cache=self._jit)
        elif p.rung == "chunked":
            s, i = _exact.exact_chunked(qp, self.device_vecs(),
                                        self.n_items, p.k,
                                        jit_cache=self._jit,
                                        exclude=exclude)
        else:
            s, i = _exact.exact_device(qp, self.device_vecs(),
                                       self.n_items, p.k,
                                       jit_cache=self._jit,
                                       exclude=exclude)
        return s[:b], i[:b], b * self.n_items

    def _execute_pq(self, q: np.ndarray, p: Plan):
        """Quantized rungs: LUT scan (IVF-pruned or full) → exact
        re-rank.  Same host-vs-device economics as the other rungs,
        judged on code rows touched (≈1 lookup ≈ 1 MAC) plus the
        re-rank matmul."""
        b = q.shape[0]
        pq = self.pq_codebook()
        host_macs = _env_int("PIO_SERVE_HOST_MACS", 2 * 10 ** 8)
        rerank_macs = b * p.rerank * self.dim
        if p.rung == "pq_flat":
            est = b * self.n_items * pq.n_tables + rerank_macs
            if est <= host_macs:
                return search_pq_host(pq, self.host_vecs(), q, p.k,
                                      p.rerank)
            qp = _pow2_pad(q)
            s, i, scanned = search_pq_device(
                pq, qp, p.k, p.rerank, jit_cache=self._jit,
                consts=self.pq_device_arrays(),
                rerank_consts=self.rerank_arrays())
            return s[:b], i[:b], int(scanned * b / max(len(qp), 1))
        idx = self.ivf_index()
        est = b * p.nprobe * idx.pad_len * pq.n_tables + rerank_macs
        if est <= host_macs:
            return search_ivf_pq_host(idx, pq, self.host_vecs(), q, p.k,
                                      p.nprobe, p.rerank)
        qp = _pow2_pad(q)
        s, i, scanned = search_ivf_pq_device(
            idx, pq, qp, p.k, p.nprobe, p.rerank, jit_cache=self._jit,
            ivf_consts=self.ivf_device_arrays(),
            pq_consts=self.pq_device_arrays(),
            rerank_consts=self.rerank_arrays())
        # scanned counts the padded batch's probes; rescale to real.
        return s[:b], i[:b], int(scanned * b / max(len(qp), 1))


def _pow2_pad(q: np.ndarray) -> np.ndarray:
    """Pad the batch to the next power of two (compiled-program menu)."""
    b = q.shape[0]
    pad = (1 << max(b - 1, 0).bit_length()) - b
    if pad:
        return np.concatenate([q, np.zeros((pad, q.shape[1]), q.dtype)])
    return q


def iter_hits(scores_row, ids_row, num: int) -> Iterator[Tuple[int, float]]:
    """(item_id, score) pairs of one result row, sentinel-padding
    skipped, at most ``num`` — the one loop every template's
    result-building shares."""
    taken = 0
    for s, i in zip(scores_row, ids_row):
        if taken >= num:
            return
        if i < 0 or s <= _NEG_SENTINEL:
            continue
        yield int(i), float(s)
        taken += 1


# -- per-model retriever cache ----------------------------------------------

_RETRIEVERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_RETRIEVERS_LOCK = threading.Lock()
_PENDING_ARM: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def cached_retriever(owner, build) -> Retriever:
    """ONE retriever per loaded model object, built lazily, dying with
    the generation (weak-keyed — a swapped-out model wrapper releases
    its staged corpus copies with itself).  Keeping the cache OUT of the
    wrapper dataclasses means nothing jit- or device-shaped ever rides
    the model pickle."""
    r = _RETRIEVERS.get(owner)
    if r is None:
        pending = None
        with _RETRIEVERS_LOCK:
            r = _RETRIEVERS.get(owner)
            if r is None:
                r = build()
                _RETRIEVERS[owner] = r
                pending = _PENDING_ARM.pop(owner, None)
        if pending is not None:
            try:
                pending(r)
            except Exception:
                logger.debug("retriever arm callback failed",
                             exc_info=True)
    return r


def arm_on_create(owner, fn) -> None:
    """Run ``fn(retriever)`` for ``owner``'s retriever — immediately if
    one is already cached, else right after ``cached_retriever`` builds
    it.  Lets observers (obs/recall.py) attach per-generation hooks
    WITHOUT forcing retriever creation at model load: creation — and
    with it index/codebook fingerprint validation — stays lazy on the
    first query.  At most one pending callback per owner (latest wins);
    a callback for a swapped-out generation is expected to no-op when
    it fires."""
    with _RETRIEVERS_LOCK:
        r = _RETRIEVERS.get(owner)
        if r is None:
            _PENDING_ARM[owner] = fn
            return
    fn(r)


def build_train_index(item_vecs: np.ndarray, *, name: str,
                      seed: Optional[int] = None,
                      require_explicit: bool = False
                      ) -> Optional[IVFIndex]:
    """Train-time IVF build under the env policy (``PIO_IVF`` /
    ``PIO_IVF_NLIST`` / ``PIO_IVF_MIN_ITEMS``) — called by template
    ``train()`` so the index is serialized inside the SAME model
    artifact the generation swap moves.

    ``require_explicit`` is for norm-variant corpora (raw ALS factors,
    popularity-scaled norms): k-means cells partition by direction, so a
    high-norm item in an unprobed cell is an unrecoverable miss — the
    index builds only under an explicit ``PIO_IVF=on``, never ``auto``.
    """
    if require_explicit:
        mode = os.environ.get("PIO_IVF", "auto").strip().lower() or "auto"
        if mode not in ("on", "1", "true", "yes"):
            logger.debug("IVF build skipped for %r: norm-variant corpus "
                         "needs explicit PIO_IVF=on (got %r)", name, mode)
            return None
    build, nlist, min_items = ivf_build_config(len(item_vecs))
    if not build:
        logger.debug("IVF build skipped for %r (n=%d < min=%d or PIO_IVF "
                     "off)", name, len(item_vecs), min_items)
        return None
    t0 = time.perf_counter()
    # seed=None (templates with no configured seed) pins to 0 — two
    # trains over identical data must build identical indexes, or recall
    # characteristics and bench comparisons drift run-to-run.
    idx = build_ivf(np.asarray(item_vecs, dtype=np.float32), nlist=nlist,
                    seed=0 if seed is None else seed, force=True)
    logger.info("IVF index for %r built in %.1fs (nlist=%d)", name,
                time.perf_counter() - t0, idx.nlist if idx else -1)
    return idx


def build_train_pq(item_vecs: np.ndarray, *, name: str,
                   ivf: Optional[IVFIndex] = None,
                   seed: Optional[int] = None) -> Optional[PQCodebook]:
    """Train-time residual-PQ build under the env policy (``PIO_PQ`` /
    ``PIO_PQ_M`` / ``PIO_PQ_MIN_ITEMS``) — called by template
    ``train()`` AFTER the IVF build so the residual coarse book can ride
    the same cell structure, and serialized inside the SAME model
    artifact the generation swap moves.

    Unlike IVF, PQ needs no norm-variance opt-in: the exact re-rank
    re-scores every returned candidate against the true embeddings, so
    quantization error orders a shortlist but never the final top-k.
    """
    vecs = np.asarray(item_vecs, dtype=np.float32)
    build, m, min_items = pq_build_config(len(vecs), vecs.shape[1])
    if not build:
        logger.debug("PQ build skipped for %r (n=%d < min=%d or PIO_PQ "
                     "off)", name, len(vecs), min_items)
        return None
    t0 = time.perf_counter()
    # seed=None pins to 0 like build_train_index — identical data must
    # build identical codes or recall/bench comparisons drift.
    pq = build_pq(vecs, m=m, ivf=ivf, seed=0 if seed is None else seed)
    logger.info("PQ codebook for %r built in %.1fs (M=%d, %d B/item)",
                name, time.perf_counter() - t0, pq.m,
                pq.bytes_per_item())
    return pq
