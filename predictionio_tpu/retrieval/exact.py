"""Exact retrieval rungs — host, single-device, chunked, mesh-sharded.

Every rung returns the SAME answer (the true top-k id set, scores within
fp tolerance — test-pinned); they differ only in where the work runs and
what memory it touches:

- ``host``    — numpy over host-resident vectors; wins whenever one
  device dispatch round-trip costs more than the matmul (B=1 serving).
- ``device``  — one jitted ``top_k_scores`` dispatch; the [B, N] score
  block materializes, fine for small/medium corpora.
- ``chunked`` — ``chunked_top_k`` scan slabs (auto-padded tail); score
  memory bounded at [B, chunk] for corpora that outgrow HBM comfort.
  On TPU the facade swaps in the fused Pallas kernel
  (``ops.pallas_kernels.fused_topk``) which never materializes even the
  slab.
- ``sharded`` — corpus row-sharded over a mesh axis, per-shard local
  top-k + O(k·shards·B) all-gather merge (``ops.topk.sharded_top_k``).

The jitted callables are cached per (rung, B, k) in a caller-owned dict
so the serving hot path is ONE cached dispatch — a fresh closure per
request would re-trace and pay eager round-trips (the exact trap the ALS
template's ``_mips_jit`` cache used to guard; that cache now lives here,
shared by every engine).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from predictionio_tpu.ops.pallas_kernels import fused_topk, pallas_supported
from predictionio_tpu.ops.topk import (
    chunked_top_k,
    host_top_k,
    sharded_top_k,
    top_k_scores,
)

__all__ = ["exact_host", "exact_device", "exact_chunked", "exact_sharded",
           "SERVE_CACHE_LOCK"]

# Guards cold-path serving cache builds (jit compiles, device staging):
# a burst of concurrent first requests on the threaded server must not
# each trace its own program or stage its own corpus copy.  One process-
# wide lock — builds are rare and short relative to what they prevent.
SERVE_CACHE_LOCK = threading.Lock()


def _cached(jit_cache: Dict, key, build):
    fn = jit_cache.get(key)
    if fn is None:
        with SERVE_CACHE_LOCK:
            fn = jit_cache.get(key)
            if fn is None:
                fn = build()
                jit_cache[key] = fn
    return fn


def exact_host(queries: np.ndarray, host_vecs: np.ndarray, k: int, *,
               exclude: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
    s, i = host_top_k(queries, host_vecs, k, exclude=exclude)
    return np.asarray(s), np.asarray(i)


def exact_device(queries: np.ndarray, items_dev, n_items: int, k: int, *,
                 jit_cache: Dict, exclude: Optional[np.ndarray] = None
                 ) -> Tuple["np.ndarray", "np.ndarray"]:
    """One top_k_scores dispatch; ONE host transfer for the results.

    The corpus-padding part of the mask (``n_items < n``) is request-
    invariant — staged on device ONCE as a [N] row and broadcast inside
    the program.  Only a per-request ``exclude`` uploads per call, at
    its own [B, ≤N] width (never a fresh host-built [B, N] block).
    """
    import jax
    import jax.numpy as jnp

    b = queries.shape[0]
    n = items_dev.shape[0]
    pad_row = None
    if n_items < n:
        pad_row = _cached(jit_cache, ("pad_row", n, n_items),
                          lambda: jnp.arange(n) >= n_items)
    has_pad = pad_row is not None
    if exclude is None:
        def build():
            def _fn(q, items, pr):
                e = jnp.broadcast_to(pr[None, :], (q.shape[0], n)) \
                    if has_pad else None
                return top_k_scores(q, items, k, exclude=e)
            return jax.jit(_fn)

        fn = _cached(jit_cache, ("device", b, k, False, has_pad), build)
        out = fn(jnp.asarray(queries, jnp.float32), items_dev, pad_row)
    else:
        ne = exclude.shape[1]

        def build():
            def _fn(q, items, e, pr):
                e = jnp.pad(e, ((0, 0), (0, n - ne)))
                if has_pad:
                    e = e | pr[None, :]
                return top_k_scores(q, items, k, exclude=e)
            return jax.jit(_fn)

        # exclude changes per request — it rides as a traced arg, so the
        # cache key only needs the static shapes.
        fn = _cached(jit_cache, ("device", b, k, True, has_pad, ne), build)
        out = fn(jnp.asarray(queries, jnp.float32), items_dev,
                 jnp.asarray(exclude), pad_row)
    s, i = jax.device_get(out)
    return np.asarray(s), np.asarray(i)


def exact_chunked(queries: np.ndarray, items_dev, n_items: int, k: int, *,
                  jit_cache: Dict, chunk: int = 262_144,
                  exclude: Optional[np.ndarray] = None
                  ) -> Tuple["np.ndarray", "np.ndarray"]:
    """Bounded-score-memory scan; fused Pallas kernel where supported.

    ``exclude`` ([B, ≤N] bool) rides the scan chunk-by-chunk — the
    Pallas kernel takes no mask, so excluded requests use the XLA scan
    (score memory stays bounded at [B, chunk] either way).
    """
    import jax
    import jax.numpy as jnp

    b = queries.shape[0]
    n = items_dev.shape[0]
    use_pallas = pallas_supported() and exclude is None
    # exclude uploads at its native [B, ≤N] width — the width-padding to
    # the staged corpus happens in-program, never as a fresh host-built
    # [B, N] block per request (same discipline as exact_device).
    ne = exclude.shape[1] if exclude is not None else None

    def build():
        if use_pallas:
            def _fn(q, items, e):
                return fused_topk(q, items, k, n_valid=n_items,
                                  use_pallas=True)
        else:
            def _fn(q, items, e):
                if e is not None and ne < n:
                    e = jnp.pad(e, ((0, 0), (0, n - ne)))
                return chunked_top_k(q, items, k,
                                     chunk=min(chunk, n),
                                     n_valid=n_items, exclude=e)
        return jax.jit(_fn)

    fn = _cached(jit_cache, ("chunked", b, k, use_pallas, ne), build)
    s, i = jax.device_get(fn(
        jnp.asarray(queries, jnp.float32), items_dev,
        jnp.asarray(exclude) if exclude is not None else None))
    return np.asarray(s), np.asarray(i)


def exact_sharded(queries: np.ndarray, items_sharded, n_items: int, k: int,
                  *, jit_cache: Dict
                  ) -> Tuple["np.ndarray", "np.ndarray"]:
    """Mesh-sharded exact: local score+top-k per shard, tiny cross-device
    merge.  ``items_sharded`` must be row-sharded with a NamedSharding
    whose dim-0 spec names a mesh axis (the facade stages it that way)."""
    import jax
    import jax.numpy as jnp

    sh = items_sharded.sharding
    mesh, axis = sh.mesh, sh.spec[0]
    b = queries.shape[0]

    def build():
        def _fn(q, items):
            return sharded_top_k(mesh, axis, q, items, k, n_valid=n_items)
        return jax.jit(_fn)

    fn = _cached(jit_cache, ("sharded", b, k), build)
    s, i = jax.device_get(fn(jnp.asarray(queries, jnp.float32),
                             items_sharded))
    return np.asarray(s), np.asarray(i)
