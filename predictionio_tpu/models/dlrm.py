"""DLRM-style CTR ranking — sharded embedding tables + dense interaction.

Absent in the reference (SURVEY.md §2.2: new build target from BASELINE
config 5).  This is the EP-shaped component of the build (§2.4): the
categorical embedding tables dominate memory, so they are **row-sharded
over the ``expert`` mesh axis**, and lookups are exchanged with XLA
collectives over ICI.

Lookup design (``sharded_embedding_lookup``): all feature tables are
concatenated into one [ΣV, E] table, row-sharded.  Inside ``shard_map``:

1. every shard all-gathers the (tiny, int32) global index batch,
2. computes masked partial embeddings for the indices it owns
   (``idx ∈ [lo, hi)`` → ``table[idx - lo]``, else 0), and
3. ``psum_scatter`` returns each batch-shard its summed rows — exactly one
   owner contributes per index, so the sum IS the lookup.

Traffic: an all-gather of int32 indices + one reduce-scatter of the
embedding activations — both nearest-neighbor ICI patterns.  (The
request/reply ``all_to_all`` variant saves bandwidth at large expert
counts; this formulation is MXU-friendlier and exact.)

Model: bottom MLP over dense features, pairwise dot-product feature
interaction (the DLRM arch), top MLP → CTR logit.  bf16 matmuls, f32
master weights, optax adagrad (the DLRM-paper optimizer).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.obs.runtime import get_compile_tracker
from predictionio_tpu.parallel.compat import shard_map
from predictionio_tpu.parallel.mesh import AXIS_EXPERT, put_sharded

__all__ = ["DLRMConfig", "DLRMState", "init_state", "train_step",
           "train_steps_fused", "train", "predict_proba",
           "sharded_embedding_lookup"]


@dataclasses.dataclass
class DLRMConfig:
    vocab_sizes: Tuple[int, ...]        # per categorical feature field
    n_dense: int                        # dense feature count
    embed_dim: int = 16
    bottom_mlp: Tuple[int, ...] = (64, 32)
    top_mlp: Tuple[int, ...] = (64, 32)
    learning_rate: float = 0.05
    batch_size: int = 512
    epochs: int = 2
    seed: int = 0

    def __post_init__(self):
        if self.bottom_mlp[-1] != self.embed_dim:
            raise ValueError(
                f"bottom_mlp[-1] ({self.bottom_mlp[-1]}) must equal embed_dim "
                f"({self.embed_dim}) — the dot interaction mixes them.")

    @property
    def total_vocab(self) -> int:
        return int(sum(self.vocab_sizes))

    @property
    def offsets(self) -> np.ndarray:
        """Row offset of each field's table in the concatenated table."""
        return np.cumsum([0, *self.vocab_sizes[:-1]]).astype(np.int32)


def _init_mlp(key, in_dim: int, dims: Sequence[int]) -> List[Dict]:
    layers = []
    all_dims = (in_dim, *dims)
    for a, b in zip(all_dims[:-1], all_dims[1:]):
        key, k = jax.random.split(key)
        layers.append({
            # max(a, 1): n_dense == 0 gives the bottom MLP a zero-width
            # input ([B,0]·[0,H] = 0 + bias) — legal, He scale undefined.
            "w": jax.random.normal(k, (a, b), jnp.float32)
            * (2.0 / max(a, 1)) ** 0.5,
            "b": jnp.zeros((b,), jnp.float32),
        })
    return layers


def _mlp(layers: List[Dict], x: jax.Array, final_relu: bool = True) -> jax.Array:
    h = x
    for i, layer in enumerate(layers):
        h = jnp.einsum("bd,dh->bh", h.astype(jnp.bfloat16),
                       layer["w"].astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32) + layer["b"]
        if final_relu or i < len(layers) - 1:
            h = jax.nn.relu(h)
    return h


def init_params(cfg: DLRMConfig) -> Dict:
    key = jax.random.PRNGKey(cfg.seed)
    ke, kb, kt = jax.random.split(key, 3)
    n_fields = len(cfg.vocab_sizes)
    # Interaction: pairwise dots among (n_fields + 1) vectors (emb + bottom).
    n_vec = n_fields + 1
    inter_dim = n_vec * (n_vec - 1) // 2 + cfg.bottom_mlp[-1]
    return {
        "embed": jax.random.normal(ke, (cfg.total_vocab, cfg.embed_dim),
                                   jnp.float32) * (cfg.embed_dim ** -0.5),
        "bottom": _init_mlp(kb, cfg.n_dense, (*cfg.bottom_mlp[:-1],
                                              cfg.bottom_mlp[-1])),
        "top": _init_mlp(kt, inter_dim, (*cfg.top_mlp, 1)),
    }


def param_shardings(cfg: DLRMConfig, mesh: Mesh):
    rep = NamedSharding(mesh, P())
    return {
        "embed": NamedSharding(mesh, P(AXIS_EXPERT, None)),
        "bottom": [jax.tree.map(lambda _: rep, l)
                   for l in init_params(cfg)["bottom"]],
        "top": [jax.tree.map(lambda _: rep, l)
                for l in init_params(cfg)["top"]],
    }


# -- the EP lookup ----------------------------------------------------------

def sharded_embedding_lookup(
    mesh: Mesh,
    table: jax.Array,     # [V, E] row-sharded over AXIS_EXPERT
    indices: jax.Array,   # [B, F] int32 global rows, batch-sharded over AXIS_EXPERT
) -> jax.Array:           # [B, F, E] batch-sharded
    """Row-sharded table lookup via all_gather(idx) + psum_scatter(rows)."""
    n_shards = mesh.shape[AXIS_EXPERT]
    v = table.shape[0]
    assert v % n_shards == 0, f"pad vocab ({v}) to a multiple of {n_shards}"
    rows_per = v // n_shards

    def local(tab, idx):  # tab: [V/S, E]; idx: [B/S, F]
        shard = jax.lax.axis_index(AXIS_EXPERT)
        idx_all = jax.lax.all_gather(idx, AXIS_EXPERT, axis=0,
                                     tiled=True)          # [B, F]
        rel = idx_all - shard * rows_per
        mine = (rel >= 0) & (rel < rows_per)
        safe = jnp.clip(rel, 0, rows_per - 1)
        part = jnp.where(mine[..., None], tab[safe], 0.0)  # [B, F, E]
        return jax.lax.psum_scatter(part, AXIS_EXPERT, scatter_dimension=0,
                                    tiled=True)            # [B/S, F, E]

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(AXIS_EXPERT, None), P(AXIS_EXPERT, None)),
        out_specs=P(AXIS_EXPERT, None, None),
    )(table, indices)


def _interact(emb: jax.Array, bottom_out: jax.Array) -> jax.Array:
    """DLRM pairwise-dot interaction: [B,F,E] x [B,E] → [B, F+1 choose 2 + D]."""
    vecs = jnp.concatenate([emb, bottom_out[:, None, :]], axis=1)  # [B,F+1,E]
    prods = jnp.einsum("bfe,bge->bfg", vecs, vecs,
                       preferred_element_type=jnp.float32)
    n = vecs.shape[1]
    iu, ju = jnp.triu_indices(n, k=1)
    flat = prods[:, iu, ju]                                        # [B, nC2]
    return jnp.concatenate([flat, bottom_out], axis=1)


def _forward(params: Dict, dense: jax.Array, cat: jax.Array,
             mesh: Optional[Mesh]) -> jax.Array:
    if mesh is not None and mesh.shape.get(AXIS_EXPERT, 1) > 1:
        emb = sharded_embedding_lookup(mesh, params["embed"], cat)
    else:
        emb = params["embed"][cat]                                 # [B, F, E]
    bottom_out = _mlp(params["bottom"], dense)                     # [B, D]
    z = _interact(emb, bottom_out)
    logit = _mlp(params["top"], z, final_relu=False)               # [B, 1]
    return logit[:, 0]


def _loss(params, dense, cat, labels, weights, mesh):
    logits = _forward(params, dense, cat, mesh)
    losses = optax.sigmoid_binary_cross_entropy(logits, labels)
    return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(weights), 1.0)


@dataclasses.dataclass
class DLRMState:
    params: Dict
    opt_state: Any
    step: jax.Array


def _tx(cfg: DLRMConfig):
    return optax.adagrad(cfg.learning_rate)


def init_state(cfg: DLRMConfig, mesh: Optional[Mesh] = None) -> DLRMState:
    params = init_params(cfg)
    if mesh is not None:
        params = jax.tree_util.tree_map(
            lambda p, s_: put_sharded(p, mesh, s_),
            params, param_shardings(cfg, mesh))
    return DLRMState(params=params, opt_state=_tx(cfg).init(params),
                     step=jnp.zeros((), jnp.int32))


class _StepKey:
    """Static-arg wrapper for (cfg, mesh) — hashed by compile-relevant bits."""

    def __init__(self, cfg: DLRMConfig, mesh: Optional[Mesh]):
        self.cfg = cfg
        self.mesh = mesh
        self._key = (cfg.learning_rate, cfg.vocab_sizes, cfg.embed_dim,
                     cfg.bottom_mlp, cfg.top_mlp,
                     tuple(sorted(mesh.shape.items())) if mesh else None)

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _StepKey) and self._key == other._key


def _step_math(state_tuple, dense, cat, labels, weights, key: _StepKey):
    """One optimizer step's pure math — shared VERBATIM by the per-step
    jit and the K-fused ``lax.scan`` body so fused training is the same
    traced computation (tests pin K=1 vs K>1 bitwise on CPU)."""
    params, opt_state, step = state_tuple
    loss, grads = jax.value_and_grad(_loss)(params, dense, cat, labels,
                                            weights, key.mesh)
    updates, opt_state = _tx(key.cfg).update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return (params, opt_state, step + 1), loss


# Batch tensors donated alongside the carried state (see two_tower): the
# prefetched pipeline stages fresh buffers per step, so donation bounds
# steady-state device memory at (prefetch depth + 1) batches.  CPU warns
# the donation was unusable — expected there (pyproject filters it for
# the test suite; where donation is real the warning stays audible).
_train_step_impl = functools.partial(
    jax.jit, static_argnames=("key",), donate_argnums=(0, 1, 2, 3, 4))(
        _step_math)


# K-step fused dispatch (ISSUE 7, see two_tower): ONE lax.scan program
# runs K optimizer steps over a K-stacked superbatch, donating state and
# the whole superbatch; returns the per-step loss vector [K] the
# divergence guard checks at the fusion boundary.
@functools.partial(jax.jit, static_argnames=("key",),
                   donate_argnums=(0, 1, 2, 3, 4))
def _fused_steps_impl(state_tuple, dense, cat, labels, weights,
                      key: _StepKey):
    def body(carry, batch):
        d, c, y, w = batch
        return _step_math(carry, d, c, y, w, key)

    return jax.lax.scan(body, state_tuple, (dense, cat, labels, weights))


# Compile tracking (obs.runtime): see two_tower — the fused entry point
# tracks under its own name so fusion-depth changes read as named
# compiles, not mystery churn.
_tracked_train_step = get_compile_tracker().wrap(
    "dlrm.train_step", _train_step_impl)
_tracked_fused_steps = get_compile_tracker().wrap(
    "dlrm.train_steps_fused", _fused_steps_impl)


def train_step(state: DLRMState, dense, cat, labels, weights,
               cfg: DLRMConfig, mesh: Optional[Mesh] = None):
    """One optimizer step.  ``state`` AND the batch tensors are donated:
    on donation-capable backends (TPU/GPU) the inputs are consumed — pass
    fresh device buffers per call (as the prefetched train loop does),
    not arrays you reuse afterwards."""
    (p, o, s), loss = _tracked_train_step(
        (state.params, state.opt_state, state.step),
        dense, cat, labels, weights, _StepKey(cfg, mesh))
    return DLRMState(params=p, opt_state=o, step=s), loss


def train_steps_fused(state: DLRMState, dense, cat, labels, weights,
                      cfg: DLRMConfig, mesh: Optional[Mesh] = None):
    """K fused optimizer steps in ONE XLA dispatch.

    Batch tensors carry a leading scan axis ([K, B, ...], staged by the
    prefetcher's superbatch assembly); state and the whole superbatch
    are donated.  Returns the carried state and the per-step loss vector
    [K].  The resulting model state is bitwise-equal to K sequential
    :func:`train_step` calls on the same batches (test-pinned on CPU;
    the observability loss scalars may sit 1 ulp off standalone
    dispatches — XLA fuses a rolled scan body's scalar output path
    differently)."""
    (p, o, s), losses = _tracked_fused_steps(
        (state.params, state.opt_state, state.step),
        dense, cat, labels, weights, _StepKey(cfg, mesh))
    return DLRMState(params=p, opt_state=o, step=s), losses


def train(
    dense: np.ndarray,      # [N, n_dense] float
    cat: np.ndarray,        # [N, F] int — PER-FIELD indices (offsets applied here)
    labels: np.ndarray,     # [N] {0,1}
    cfg: DLRMConfig,
    mesh: Optional[Mesh] = None,
    *,
    checkpoint_dir=None,
    save_every: int = 0,
    data_source: str = "auto",
    fuse_steps=None,
    warm_state: Optional[DLRMState] = None,
) -> DLRMState:
    """Minibatch CTR training.

    ``warm_state`` (ISSUE 10): continue from an existing state (the
    previous generation's) on a delta window instead of a fresh init —
    DLRM's hashed vocabularies are fixed-size, so no table growth is
    needed and any unseen entity already lands in a shared bucket.

    ``data_source`` mirrors two_tower.train: "feeder" streams batches
    from the native mmap cache (v3: any number of categorical columns —
    real CTR shapes have tens — the label on the value column, dense
    features on the extras columns); "numpy" is the host permutation;
    "auto" picks the feeder whenever the native library builds.
    ``checkpoint_dir`` + ``save_every`` give mid-training resume with
    deterministic per-(seed, epoch) batch order in both sources.

    Supervision mirrors two_tower.train: divergence rollback to the
    last-good checkpoint (bounded, then ``TrainDiverged``), SIGTERM
    preemption (``TrainPreempted`` after a final checkpoint), and the
    ``PIO_STEP_TIMEOUT_S`` step watchdog.

    ``fuse_steps`` mirrors two_tower.train: K optimizer steps fused into
    one ``lax.scan`` dispatch (bitwise-equal to K=1), ``"auto"`` grows
    depth until the HBM headroom guardrail pushes back; supervision
    moves to the fusion boundary (scaled watchdog deadline, per-step
    loss-vector divergence check, boundary-aligned checkpoints).
    """
    from predictionio_tpu.resilience.supervision import (
        DivergenceGuard,
        RollbackRequested,
    )

    # Without a checkpointer a "rollback" is a full deterministic retrain
    # that reproduces the same NaN — terminal immediately (max 0), same
    # policy as als.py.
    can_rollback = bool(checkpoint_dir) and save_every > 0
    guard = DivergenceGuard("dlrm",
                            max_rollbacks=None if can_rollback else 0)
    while True:
        try:
            return _train_attempt(dense, cat, labels, cfg, mesh,
                                  checkpoint_dir=checkpoint_dir,
                                  save_every=save_every,
                                  data_source=data_source, guard=guard,
                                  fuse_steps=fuse_steps,
                                  warm_state=warm_state)
        except RollbackRequested:
            continue  # re-enter: restore_step fast-forwards to last-good


def _train_attempt(
    dense: np.ndarray,
    cat: np.ndarray,
    labels: np.ndarray,
    cfg: DLRMConfig,
    mesh: Optional[Mesh],
    *,
    checkpoint_dir,
    save_every: int,
    data_source: str,
    guard,
    fuse_steps=None,
    warm_state: Optional[DLRMState] = None,
) -> DLRMState:
    from predictionio_tpu.resilience.supervision import (
        StepWatchdog,
        TrainPreempted,
        preemption_requested,
    )
    from predictionio_tpu.workflow.checkpoint import TrainCheckpointer

    n = len(labels)
    cat = np.asarray(cat)
    cat_global = (np.asarray(cat, np.int64) + cfg.offsets[None, :]).astype(np.int32)
    state = warm_state if warm_state is not None else init_state(cfg, mesh)
    total_steps = cfg.epochs * ((n + cfg.batch_size - 1) // cfg.batch_size)
    # Warm continuations fingerprint on the carried step: a crash-resume
    # checkpoint from a different base generation must not restore here.
    fp_extra = f"|warm@{int(jax.device_get(state.step))}" \
        if warm_state is not None else ""
    ckpt = TrainCheckpointer(checkpoint_dir or ".", save_every=save_every
                             if checkpoint_dir else 0,
                             fingerprint=f"dlrm|{cfg}|n={n}{fp_extra}")
    watchdog = StepWatchdog("dlrm", checkpoint_fn=ckpt.flush)
    start_step = ckpt.restore_step(
        (state.params, state.opt_state, state.step), total_steps=total_steps)
    if ckpt.restored_state is not None:
        p, o, s = ckpt.restored_state
        state = DLRMState(params=p, opt_state=o, step=s)
    bs = cfg.batch_size
    sh = NamedSharding(mesh, P(AXIS_EXPERT)) if mesh is not None else None

    def numpy_epochs():
        for epoch in range(cfg.epochs):
            order = np.random.default_rng(cfg.seed + epoch).permutation(n)
            for start in range(0, n, bs):
                sel = order[start:start + bs]
                yield (dense[sel], cat_global[sel],
                       labels[sel].astype(np.float32))

    def feeder_epochs():
        import tempfile

        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        with tempfile.TemporaryDirectory(prefix="pio_dlrm_cache_") as d:
            # v3 cache: F categorical columns (any CTR shape), the label
            # on the value column, dense features on the extras columns.
            cache = write_cache(
                f"{d}/train.piof",
                cats=cat_global.astype(np.uint32),
                values=np.asarray(labels, np.float32),
                extras=(np.asarray(dense, np.float32)
                        if cfg.n_dense else None))
            with EventFeeder(cache, bs, seed=cfg.seed) as f:
                for _ in range(cfg.epochs):
                    for batch in f.epoch_cats():
                        c, y = batch[0], batch[1]
                        extras = (batch[2] if len(batch) > 2 else
                                  np.zeros((len(y), 0), np.float32))
                        yield extras, c.astype(np.int32), y

    use_feeder = data_source == "feeder"
    if data_source == "auto":
        from predictionio_tpu.native.build import load_library

        use_feeder = load_library("feeder") is not None
    # Overlapped input pipeline (ISSUE 5 / data/prefetch.py): padding +
    # dtype conversion + H2D run on a background prep thread so batch
    # N+1's transfer rides under batch N's device step (see two_tower).
    # K-step fusion (ISSUE 7 / data/fusion.py): superbatch staging + ONE
    # lax.scan dispatch per window, supervision at the window boundary.
    from predictionio_tpu.data.fusion import (
        FusionAutotuner,
        FusionPlan,
        crossed_save_point,
        fuse_steps_config,
        slot_steps,
    )
    from predictionio_tpu.data.prefetch import DevicePrefetcher
    from predictionio_tpu.obs import PipelineProbe

    n_fields = cat.shape[1]

    def prep(batch):
        # Prep-thread staging: identical layout/dtypes to the historical
        # inline path (tests pin bitwise equivalence on CPU).
        d, c, y = batch
        pad = bs - len(y)
        return (
            np.asarray(np.concatenate(
                [d, np.zeros((pad, cfg.n_dense), np.float32)]), np.float32),
            np.concatenate([c, np.zeros((pad, n_fields), np.int32)]),
            np.asarray(np.concatenate(
                [y, np.zeros(pad, np.float32)]), np.float32),
            np.concatenate([np.ones(len(y), np.float32),
                            np.zeros(pad, np.float32)]),
        )

    put = None
    fused_put = None
    if sh is not None:
        def put(arrays):
            return tuple(put_sharded(a, mesh, sh) for a in arrays)

        # Superbatch staging: batch axis moves to dim 1 under the scan
        # axis, so shard dim 1 and replicate the leading axis.
        fused_sh = NamedSharding(mesh, P(None, AXIS_EXPERT))

        def fused_put(arrays):
            return tuple(put_sharded(a, mesh, fused_sh) for a in arrays)

    k0, auto = fuse_steps_config(fuse_steps)
    plan = FusionPlan(k0)
    tuner = FusionAutotuner("dlrm", plan) if auto else None

    probe = PipelineProbe("dlrm")
    global_step = start_step
    pending = None  # (losses, slot steps) of the in-flight dispatch
    in_flight = 0  # raw steps covered by the in-flight dispatch
    try:
        with DevicePrefetcher(
                feeder_epochs() if use_feeder else numpy_epochs(),
                prep, put_fn=put, fused_put_fn=fused_put,
                skip_steps=start_step, fuse_plan=plan,
                model="dlrm") as pf:
            for batch in probe.iter_prefetched(pf):
                global_step = batch.step
                # Deadline covers the LONGER of the in-flight dispatch
                # (the sync below blocks on dispatch N-1 — possibly a
                # deeper window than this batch, e.g. a K=1 tail flush
                # behind a K=32 window) and this batch's own dispatch.
                watchdog.arm(global_step,
                             scale=max(batch.steps, in_flight))
                probe.sync()  # wait on dispatch N-1: its state feeds N
                if pending is not None:
                    # Dispatch N-1's losses materialized with the sync
                    # above — every slot checked at the fusion boundary.
                    guard.check_vector(*pending)
                if batch.k > 1:
                    state, losses = train_steps_fused(state, *batch.args,
                                                      cfg, mesh)
                else:
                    state, losses = train_step(state, *batch.args, cfg,
                                               mesh)
                pending = (losses, slot_steps(batch))
                in_flight = batch.steps
                # Sync target includes the losses: the next boundary's
                # divergence check reads them materialized, and the wait
                # bills to device_wait where it belongs.
                probe.dispatched((state, losses), examples=batch.examples,
                                 steps=batch.steps)
                saved = False
                if ckpt.enabled and crossed_save_point(
                        global_step, batch.steps, ckpt.save_every):
                    # Fresh watchdog deadline: the forced loss-vector
                    # check blocks on the device and a hang here must
                    # fire too.  Checkpoints land on fusion boundaries —
                    # never a NaN state, never mid-window.
                    watchdog.arm(global_step, scale=batch.steps)
                    guard.check_vector(*pending)
                    if global_step % ckpt.save_every == 0:
                        saved = ckpt.maybe_save(
                            global_step,
                            (state.params, state.opt_state, state.step))
                    else:
                        # Window boundary just past the cadence point.
                        ckpt.save(global_step,
                                  (state.params, state.opt_state,
                                   state.step))
                        saved = True
                watchdog.disarm()
                if tuner is not None:
                    tuner.on_window()
                if preemption_requested():
                    if ckpt.enabled and not saved:
                        ckpt.save(global_step,
                                  (state.params, state.opt_state,
                                   state.step))
                    ckpt.flush()
                    raise TrainPreempted("dlrm", global_step, ckpt.enabled)
        probe.finish()
        if pending is not None:
            guard.check_vector(*pending)
        guard.check_params(state.params, global_step)
        ckpt.complete()
    finally:
        watchdog.stop()
        ckpt.close()
    return state


def predict_proba(state: DLRMState, dense: np.ndarray, cat: np.ndarray,
                  cfg: DLRMConfig, mesh: Optional[Mesh] = None) -> jax.Array:
    cat_global = (np.asarray(cat, np.int64) + cfg.offsets[None, :]).astype(np.int32)
    logits = _forward(state.params, jnp.asarray(dense, jnp.float32),
                      jnp.asarray(cat_global), mesh)
    return jax.nn.sigmoid(logits)
