"""Two-tower neural retrieval — the TPU-era flagship engine.

Absent in the reference (SURVEY.md §2.2 marks it a new build target from
BASELINE.json config 4): learned user/item embeddings + MLP towers trained
with in-batch sampled-softmax negatives, retrieval = MIPS top-K over item
embeddings.

TPU design:
- batch sharded over the ``data`` mesh axis (DP); the in-batch-negatives
  logits matrix is [B, B] — each shard computes its slice against the
  all-gathered item embeddings of the global batch (XLA inserts the
  all-gather from the sharding annotations; it rides ICI).
- embedding tables row-sharded over the ``model`` axis (the tables dominate
  memory); MLP weights replicated (tiny).
- matmuls in bfloat16 with f32 accumulation (MXU-native), params in f32.
- the whole train step is ONE jitted function: grads via ``jax.grad``,
  optax adam update inside.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.obs.runtime import get_compile_tracker
from predictionio_tpu.ops.topk import top_k_scores
from predictionio_tpu.parallel.mesh import AXIS_DATA, AXIS_MODEL, put_sharded

__all__ = ["TwoTowerConfig", "TwoTowerState", "init_state", "train_step",
           "train_steps_fused", "train", "grow_state", "state_to_host",
           "state_from_host", "encode_users", "encode_items", "retrieve"]


@dataclasses.dataclass
class TwoTowerConfig:
    n_users: int
    n_items: int
    embed_dim: int = 64
    hidden_dims: Tuple[int, ...] = (128,)
    out_dim: int = 64
    learning_rate: float = 1e-3
    temperature: float = 0.05
    batch_size: int = 1024
    epochs: int = 5
    seed: int = 0


def _init_mlp(key, in_dim: int, hidden: Tuple[int, ...], out_dim: int) -> Dict:
    layers = []
    dims = (in_dim, *hidden, out_dim)
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        key, k = jax.random.split(key)
        layers.append({
            "w": jax.random.normal(k, (a, b), jnp.float32) * (2.0 / a) ** 0.5,
            "b": jnp.zeros((b,), jnp.float32),
        })
    return {"layers": layers}


def _mlp(params: Dict, x: jax.Array) -> jax.Array:
    h = x.astype(jnp.bfloat16)
    n = len(params["layers"])
    for i, layer in enumerate(params["layers"]):
        h = jnp.einsum("bd,dh->bh", h, layer["w"].astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        h = h + layer["b"]
        if i < n - 1:
            h = jax.nn.relu(h)
        h = h.astype(jnp.bfloat16)
    return h.astype(jnp.float32)


def init_params(cfg: TwoTowerConfig) -> Dict:
    key = jax.random.PRNGKey(cfg.seed)
    ku, ki, ku2, ki2 = jax.random.split(key, 4)
    scale = cfg.embed_dim ** -0.5
    return {
        "user_embed": jax.random.normal(ku, (cfg.n_users, cfg.embed_dim)) * scale,
        "item_embed": jax.random.normal(ki, (cfg.n_items, cfg.embed_dim)) * scale,
        "user_mlp": _init_mlp(ku2, cfg.embed_dim, cfg.hidden_dims, cfg.out_dim),
        "item_mlp": _init_mlp(ki2, cfg.embed_dim, cfg.hidden_dims, cfg.out_dim),
    }


@dataclasses.dataclass
class TwoTowerState:
    params: Dict
    opt_state: Any
    step: jax.Array


def _tx(cfg: TwoTowerConfig):
    return optax.adam(cfg.learning_rate)


def init_state(cfg: TwoTowerConfig, mesh: Optional[Mesh] = None) -> TwoTowerState:
    params = init_params(cfg)
    if mesh is not None:
        params = jax.tree_util.tree_map(
            lambda p, sh: put_sharded(p, mesh, sh),
            params, param_shardings(cfg, mesh))
    opt_state = _tx(cfg).init(params)
    return TwoTowerState(params=params, opt_state=opt_state,
                         step=jnp.zeros((), jnp.int32))


def state_to_host(state: TwoTowerState) -> Dict:
    """Host-numpy snapshot of a train state for persistence inside a
    model wrapper (the warm-start carry of ISSUE 10).  Exact f32 values —
    the round-trip is bitwise (test-pinned), so a warm-started
    continuation equals continuing in-process."""
    params, opt_state, step = jax.device_get(
        (state.params, state.opt_state, state.step))
    to_np = lambda t: jax.tree.map(np.asarray, t)  # noqa: E731
    return {"params": to_np(params), "opt_state": to_np(opt_state),
            "step": np.asarray(step)}


def state_from_host(snapshot: Dict) -> TwoTowerState:
    """Rebuild a live state from :func:`state_to_host` output.  Leaves
    stay host-backed numpy; the first dispatch uploads them."""
    return TwoTowerState(
        params=jax.tree.map(jnp.asarray, snapshot["params"]),
        opt_state=jax.tree.map(jnp.asarray, snapshot["opt_state"]),
        step=jnp.asarray(snapshot["step"]))


def grow_state(state: TwoTowerState, cfg: TwoTowerConfig) -> TwoTowerState:
    """Grow the embedding tables for entities first seen in a delta
    window (warm-start refresh, ISSUE 10).

    Existing rows keep their trained values AND their adam moments; new
    rows get a fresh deterministic init (keyed off ``cfg.seed`` and the
    CURRENT table height, so two refreshes growing by different deltas
    never collide on init noise) with zero moments — exactly what a
    cold table row would have seen.  ``cfg`` carries the NEW
    ``n_users``/``n_items``.
    """
    params = dict(state.params)
    scale = cfg.embed_dim ** -0.5

    def grown(table: jax.Array, n_total: int, salt: int) -> jax.Array:
        n_old = table.shape[0]
        if n_total <= n_old:
            return table
        key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed),
                                 salt * 1_000_003 + n_old)
        fresh = jax.random.normal(
            key, (n_total - n_old, cfg.embed_dim)) * scale
        return jnp.concatenate([table, fresh], axis=0)

    params["user_embed"] = grown(params["user_embed"], cfg.n_users, 1)
    params["item_embed"] = grown(params["item_embed"], cfg.n_items, 2)
    # Optimizer moments: a fresh init for the new shapes gives zeroed
    # slots everywhere; copy the old leaves back in (same-shape leaves
    # whole, row-grown tables as a prefix write).
    fresh_opt = _tx(cfg).init(params)

    def merge(old_leaf, fresh_leaf):
        old_leaf = jnp.asarray(old_leaf)
        if old_leaf.shape == jnp.shape(fresh_leaf):
            return old_leaf
        return jnp.asarray(fresh_leaf).at[: old_leaf.shape[0]].set(old_leaf)

    opt_state = jax.tree.map(merge, state.opt_state, fresh_opt)
    return TwoTowerState(params=params, opt_state=opt_state,
                         step=state.step)


def param_shardings(cfg: TwoTowerConfig, mesh: Mesh):
    """Embedding tables row-sharded over ``model``; MLPs replicated."""
    def shard(path_leaf):
        return NamedSharding(mesh, P(AXIS_MODEL, None))

    rep = NamedSharding(mesh, P())
    return {
        "user_embed": shard("user_embed"),
        "item_embed": shard("item_embed"),
        "user_mlp": jax.tree.map(lambda _: rep, init_params(cfg)["user_mlp"]),
        "item_mlp": jax.tree.map(lambda _: rep, init_params(cfg)["item_mlp"]),
    }


def _forward_users(params: Dict, user_ids: jax.Array) -> jax.Array:
    e = params["user_embed"][user_ids]
    z = _mlp(params["user_mlp"], e)
    return z / (jnp.linalg.norm(z, axis=-1, keepdims=True) + 1e-6)


def _forward_items(params: Dict, item_ids: jax.Array) -> jax.Array:
    e = params["item_embed"][item_ids]
    z = _mlp(params["item_mlp"], e)
    return z / (jnp.linalg.norm(z, axis=-1, keepdims=True) + 1e-6)


def _loss(params: Dict, user_ids, item_ids, weights, temperature: float):
    """In-batch sampled softmax: positives on the diagonal.

    Duplicate items inside the batch are masked out of the negatives (the
    standard correction — otherwise a repeated positive is its own negative).
    Weight-0 padding rows (trailing partial batch) are likewise masked out
    of every row's negative columns — otherwise item 0's embedding is
    injected pad-many times as a spurious negative.  Each row keeps its own
    diagonal so no row is fully masked.
    """
    u = _forward_users(params, user_ids)       # [B, D]
    v = _forward_items(params, item_ids)       # [B, D]
    logits = jnp.einsum("bd,cd->bc", u, v,
                        preferred_element_type=jnp.float32) / temperature
    same = item_ids[:, None] == item_ids[None, :]
    pad_col = (weights <= 0.0)[None, :]
    mask = (same | pad_col) & ~jnp.eye(item_ids.shape[0], dtype=bool)
    logits = jnp.where(mask, -1e9, logits)
    labels = jnp.arange(item_ids.shape[0])
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.sum(losses * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def _step_math(state: Tuple, user_ids, item_ids, weights, cfg) -> Tuple:
    """One optimizer step's pure math — shared VERBATIM by the per-step
    jit and the K-fused ``lax.scan`` body so fused training is the same
    traced computation (tests pin K=1 vs K>1 bitwise on CPU)."""
    params, opt_state, step = state
    loss, grads = jax.value_and_grad(_loss)(params, user_ids, item_ids,
                                            weights, cfg.temperature)
    updates, opt_state = _tx(cfg).update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return (params, opt_state, step + 1), loss


# Batch tensors are donated along with the carried state: each step
# consumes its staged batch exactly once (data/prefetch.py creates fresh
# device buffers per step), so donation lets the allocator reclaim the
# batch memory at dispatch instead of waiting for Python GC — with a
# prefetch queue holding `depth` staged batches, that bounds steady-state
# device memory at (depth + 1) batches instead of growing with GC lag.
# Backends without donation support (CPU) warn the donation was unusable;
# expected there (pyproject filters it for the CPU test suite; anywhere
# donation is real the warning stays audible — it would mean the memory
# bound above is not holding).
_train_step_impl = functools.partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0, 1, 2, 3))(
        _step_math)


# K-step fused dispatch (ISSUE 7): ONE XLA program runs K optimizer
# steps via lax.scan over a K-stacked superbatch — the per-step
# dispatch/sync cadence (BENCH_r06: ~99% of the residual pipeline gap
# is device_wait) is paid once per K steps.  The whole superbatch is
# donated like the single-step batch.  Returns the carried state and
# the per-step loss vector [K] — the divergence guard checks every slot
# at the fusion boundary.
@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnums=(0, 1, 2, 3))
def _fused_steps_impl(state: Tuple, user_ids, item_ids, weights,
                      cfg) -> Tuple:
    def body(carry, batch):
        u, i, w = batch
        return _step_math(carry, u, i, w, cfg)

    return jax.lax.scan(body, state, (user_ids, item_ids, weights))


# Compile tracking (obs.runtime): cache growth across a call = an XLA
# compilation, exported as pio_xla_compile_total{fn=...} + shape-churn
# warnings.  The fused entry point tracks under its own name, so a
# fusion-depth change shows up as a named compile, not mystery churn.
_tracked_train_step = get_compile_tracker().wrap(
    "two_tower.train_step", _train_step_impl)
_tracked_fused_steps = get_compile_tracker().wrap(
    "two_tower.train_steps_fused", _fused_steps_impl)


# dataclasses aren't pytrees; tuple in/out keeps jit donation simple.
def train_step(state: TwoTowerState, user_ids, item_ids, weights,
               cfg: TwoTowerConfig) -> Tuple[TwoTowerState, jax.Array]:
    """One optimizer step.  ``state`` AND the batch tensors are donated:
    on donation-capable backends (TPU/GPU) the inputs are consumed — pass
    fresh device buffers per call (as the prefetched train loop does),
    not arrays you reuse afterwards."""
    hcfg = _HashableConfig(cfg)
    (p, o, s), loss = _tracked_train_step(
        (state.params, state.opt_state, state.step),
        user_ids, item_ids, weights, hcfg)
    return TwoTowerState(params=p, opt_state=o, step=s), loss


def train_steps_fused(state: TwoTowerState, user_ids, item_ids, weights,
                      cfg: TwoTowerConfig) -> Tuple[TwoTowerState, jax.Array]:
    """K fused optimizer steps in ONE XLA dispatch.

    The batch tensors carry a leading scan axis ([K, B] / [K, B, ...],
    staged by the prefetcher's superbatch assembly); state and the whole
    superbatch are donated.  Returns the carried state and the per-step
    loss vector [K].  The resulting model state is bitwise-equal to K
    sequential :func:`train_step` calls on the same batches (test-pinned
    on CPU; the observability loss scalars may sit 1 ulp off standalone
    dispatches — XLA fuses a rolled scan body's scalar output path
    differently)."""
    hcfg = _HashableConfig(cfg)
    (p, o, s), losses = _tracked_fused_steps(
        (state.params, state.opt_state, state.step),
        user_ids, item_ids, weights, hcfg)
    return TwoTowerState(params=p, opt_state=o, step=s), losses


class _HashableConfig:
    """Static-arg wrapper: hash by the fields that change compilation."""

    def __init__(self, cfg: TwoTowerConfig):
        self._cfg = cfg
        self._key = (cfg.temperature, cfg.learning_rate)

    def __getattr__(self, name):
        return getattr(self._cfg, name)

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _HashableConfig) and self._key == other._key


def train(
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    cfg: TwoTowerConfig,
    mesh: Optional[Mesh] = None,
    weights: Optional[np.ndarray] = None,
    *,
    checkpoint_dir=None,
    save_every: int = 0,
    data_source: str = "auto",
    fuse_steps=None,
    warm_state: Optional[TwoTowerState] = None,
) -> TwoTowerState:
    """Minibatch training loop over interaction pairs.

    ``warm_state`` (ISSUE 10): continue from an existing state instead of
    a fresh init — the delta warm-start path.  The state must already
    match ``cfg``'s table heights (grow via :func:`grow_state` first);
    ``user_ids``/``item_ids`` then carry only the delta window's
    interactions.  Identical loop otherwise: same prefetcher, fusion,
    supervision, and checkpoint semantics ride both modes, and the
    result is bitwise what in-process continued training on the same
    batches would produce (test-pinned).

    The trailing ragged batch is padded with weight-0 rows — fixed shapes,
    one compilation (SURVEY.md §7 recompilation discipline).  With
    ``checkpoint_dir`` + ``save_every``, the loop checkpoints via orbax and
    resumes mid-epoch after a crash (deterministic per-epoch shuffles make
    batch order reconstructible, so skipped batches are exact).

    ``data_source``: "feeder" pulls epochs from the native mmap event
    cache (native/feeder.cc — batch assembly in C++, off the Python
    loop); "numpy" keeps host permutation; "auto" uses the feeder when
    the native library builds.  Both sources cover the dataset exactly
    once per epoch with a deterministic per-(seed, epoch) shuffle; only
    the permutation differs (tests/test_native.py pins feeder-vs-numpy
    training equivalence).

    Supervision (resilience/supervision.py): a non-finite loss rolls the
    run back to the last-good checkpoint (bounded retries, then
    ``TrainDiverged`` — a NaN model is never returned/persisted);
    SIGTERM preemption checkpoints and raises ``TrainPreempted``; with
    ``PIO_STEP_TIMEOUT_S`` set, a hung device step fires the watchdog
    instead of blocking forever.

    ``fuse_steps`` (default: env ``PIO_FUSE_STEPS``, else 1): fuse K
    optimizer steps into one XLA dispatch (``lax.scan`` over a K-stacked
    superbatch the prefetcher assembles) — bitwise-equal to K=1,
    dispatch/sync paid once per K steps.  ``"auto"`` starts at 1 and
    grows depth between rounds until the HBM headroom guardrail pushes
    back (data/fusion.py).  Supervision moves to the fusion boundary:
    the watchdog deadline scales by K, the divergence guard checks the
    per-step loss vector, and checkpoints land on window boundaries so a
    rollback target never splits a window.
    """
    from predictionio_tpu.resilience.supervision import (
        DivergenceGuard,
        RollbackRequested,
    )

    # Without a checkpointer a "rollback" is a full deterministic retrain
    # that reproduces the same NaN — terminal immediately (max 0), same
    # policy as als.py.
    can_rollback = bool(checkpoint_dir) and save_every > 0
    guard = DivergenceGuard("two_tower",
                            max_rollbacks=None if can_rollback else 0)
    while True:
        try:
            return _train_attempt(user_ids, item_ids, cfg, mesh, weights,
                                  checkpoint_dir=checkpoint_dir,
                                  save_every=save_every,
                                  data_source=data_source, guard=guard,
                                  fuse_steps=fuse_steps,
                                  warm_state=warm_state)
        except RollbackRequested:
            continue  # re-enter: restore_step fast-forwards to last-good


def _train_attempt(
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    cfg: TwoTowerConfig,
    mesh: Optional[Mesh],
    weights: Optional[np.ndarray],
    *,
    checkpoint_dir,
    save_every: int,
    data_source: str,
    guard,
    fuse_steps=None,
    warm_state: Optional[TwoTowerState] = None,
) -> TwoTowerState:
    from predictionio_tpu.resilience.supervision import (
        StepWatchdog,
        TrainPreempted,
        preemption_requested,
    )
    from predictionio_tpu.workflow.checkpoint import TrainCheckpointer

    n = len(user_ids)
    if weights is None:
        weights = np.ones(n, dtype=np.float32)
    state = warm_state if warm_state is not None else init_state(cfg, mesh)
    total_steps = cfg.epochs * ((n + cfg.batch_size - 1) // cfg.batch_size)
    # Warm continuations fingerprint on the carried step too: a crash-
    # resume checkpoint from a DIFFERENT base generation must not be
    # restored into this delta.
    fp_extra = f"|warm@{int(jax.device_get(state.step))}" \
        if warm_state is not None else ""
    ckpt = TrainCheckpointer(checkpoint_dir or ".", save_every=save_every
                             if checkpoint_dir else 0,
                             fingerprint=f"two_tower|{cfg}|n={n}{fp_extra}")
    watchdog = StepWatchdog("two_tower", checkpoint_fn=ckpt.flush)
    start_step = ckpt.restore_step(
        (state.params, state.opt_state, state.step), total_steps=total_steps)
    if ckpt.restored_state is not None:
        p, o, s = ckpt.restored_state
        state = TwoTowerState(params=p, opt_state=o, step=s)
    bs = cfg.batch_size
    batch_sharding = NamedSharding(mesh, P(AXIS_DATA)) if mesh is not None else None

    def numpy_epochs():
        for epoch in range(cfg.epochs):
            order = np.random.default_rng(cfg.seed + epoch).permutation(n)
            for start in range(0, n, bs):
                sel = order[start:start + bs]
                yield user_ids[sel], item_ids[sel], weights[sel]

    def feeder_epochs():
        import tempfile

        from predictionio_tpu.native.feeder import EventFeeder, write_cache

        with tempfile.TemporaryDirectory(prefix="pio_tt_cache_") as d:
            cache = write_cache(f"{d}/train.piof",
                                np.asarray(user_ids, np.uint32),
                                np.asarray(item_ids, np.uint32),
                                np.asarray(weights, np.float32))
            with EventFeeder(cache, bs, seed=cfg.seed) as f:
                for _ in range(cfg.epochs):
                    yield from f.epoch()

    use_feeder = data_source == "feeder"
    if data_source == "auto":
        from predictionio_tpu.native.build import load_library

        use_feeder = load_library("feeder") is not None
    # Overlapped input pipeline (ISSUE 5 / data/prefetch.py): tail-batch
    # padding + dtype conversion + the device transfer run on a
    # background prep thread, double-buffered, so batch N+1's H2D rides
    # under batch N's device step.  The probe attributes the staging to
    # the overlap window; only the queue wait stays on the step loop.
    # K-step fusion (ISSUE 7 / data/fusion.py): the prefetcher stacks K
    # prepped batches into one superbatch and the loop dispatches ONE
    # lax.scan program per window — supervision sits at the window
    # boundary.
    from predictionio_tpu.data.fusion import (
        FusionAutotuner,
        FusionPlan,
        crossed_save_point,
        fuse_steps_config,
        slot_steps,
    )
    from predictionio_tpu.data.prefetch import DevicePrefetcher
    from predictionio_tpu.obs import PipelineProbe

    def prep(batch):
        # Prep-thread staging: identical layout/dtypes to the historical
        # inline path (tests pin bitwise equivalence on CPU).
        u, i, w = batch
        pad = bs - len(u)
        return (
            np.concatenate([np.asarray(u, np.int64),
                            np.zeros(pad, np.int64)]).astype(np.int32),
            np.concatenate([np.asarray(i, np.int64),
                            np.zeros(pad, np.int64)]).astype(np.int32),
            np.concatenate([np.asarray(w, np.float32),
                            np.zeros(pad, np.float32)]),
        )

    put = None
    fused_put = None
    if batch_sharding is not None:
        def put(arrays):
            return tuple(put_sharded(a, mesh, batch_sharding)
                         for a in arrays)

        # Superbatches carry a leading scan axis: the batch axis moves
        # to dim 1, so the fused staging shards dim 1 and replicates the
        # scan axis.
        fused_sharding = NamedSharding(mesh, P(None, AXIS_DATA))

        def fused_put(arrays):
            return tuple(put_sharded(a, mesh, fused_sharding)
                         for a in arrays)

    k0, auto = fuse_steps_config(fuse_steps)
    plan = FusionPlan(k0)
    tuner = FusionAutotuner("two_tower", plan) if auto else None

    probe = PipelineProbe("two_tower")
    global_step = start_step
    pending = None  # (losses, slot steps) of the in-flight dispatch
    in_flight = 0  # raw steps covered by the in-flight dispatch
    try:
        with DevicePrefetcher(
                feeder_epochs() if use_feeder else numpy_epochs(),
                prep, put_fn=put, fused_put_fn=fused_put,
                skip_steps=start_step, fuse_plan=plan,
                model="two_tower") as pf:
            for batch in probe.iter_prefetched(pf):
                global_step = batch.step
                # Deadline covers the LONGER of the in-flight dispatch
                # (the sync below blocks on dispatch N-1 — possibly a
                # deeper window than this batch, e.g. a K=1 tail flush
                # behind a K=32 window) and this batch's own dispatch.
                watchdog.arm(global_step,
                             scale=max(batch.steps, in_flight))
                probe.sync()  # wait on dispatch N-1: its state feeds N
                if pending is not None:
                    # Dispatch N-1's losses materialized with the sync
                    # above — every slot of its window is checked at the
                    # fusion boundary for one host read of K floats.
                    guard.check_vector(*pending)
                if batch.k > 1:
                    state, losses = train_steps_fused(state, *batch.args,
                                                      cfg)
                else:
                    state, losses = train_step(state, *batch.args, cfg)
                pending = (losses, slot_steps(batch))
                in_flight = batch.steps
                # Sync target includes the losses: the next boundary's
                # divergence check reads them materialized, and the wait
                # bills to device_wait where it belongs.
                probe.dispatched((state, losses), examples=batch.examples,
                                 steps=batch.steps)
                saved = False
                if ckpt.enabled and crossed_save_point(
                        global_step, batch.steps, ckpt.save_every):
                    # Never checkpoint unvalidated state: force this
                    # window's losses (rare — only at the save cadence)
                    # so a rollback target is always finite AND always a
                    # fusion boundary.  Re-armed with a fresh deadline
                    # first: the materialization blocks on the device,
                    # and a hang HERE must fire the watchdog too.
                    watchdog.arm(global_step, scale=batch.steps)
                    guard.check_vector(*pending)
                    if global_step % ckpt.save_every == 0:
                        saved = ckpt.maybe_save(
                            global_step,
                            (state.params, state.opt_state, state.step))
                    else:
                        # Window boundary just past the cadence point.
                        ckpt.save(global_step,
                                  (state.params, state.opt_state,
                                   state.step))
                        saved = True
                watchdog.disarm()
                if tuner is not None:
                    tuner.on_window()
                if preemption_requested():
                    if ckpt.enabled and not saved:
                        ckpt.save(global_step,
                                  (state.params, state.opt_state,
                                   state.step))
                    ckpt.flush()
                    raise TrainPreempted("two_tower", global_step,
                                         ckpt.enabled)
        probe.finish()
        if pending is not None:
            guard.check_vector(*pending)
        guard.check_params(state.params, global_step)
        ckpt.complete()
    finally:
        # Close on EVERY path: a rollback re-entry reopens the directory
        # and must not race this attempt's in-flight async saves.
        watchdog.stop()
        ckpt.close()
    return state


def eval_loss(params: Dict, user_ids, item_ids, cfg: TwoTowerConfig) -> float:
    """In-batch sampled-softmax loss of ``params`` on one interaction
    sample — the warm-start regression gate's comparable scalar (same
    sample, same temperature, before vs after continuation)."""
    u = jnp.asarray(np.asarray(user_ids, np.int32))
    i = jnp.asarray(np.asarray(item_ids, np.int32))
    w = jnp.ones(u.shape[0], jnp.float32)
    return float(_loss(params, u, i, w, cfg.temperature))


def encode_users(params: Dict, user_ids: jax.Array) -> jax.Array:
    return _forward_users(params, user_ids)


def encode_items(params: Dict, item_ids: jax.Array) -> jax.Array:
    return _forward_items(params, item_ids)


def retrieve(params: Dict, user_ids: jax.Array, n_items: int, k: int,
             *, chunk: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Top-k MIPS over all item embeddings (train-side eval utility —
    serving goes through :mod:`predictionio_tpu.retrieval`).

    With ``chunk`` the scan rides :func:`ops.pallas_kernels.fused_topk`:
    on TPU the fused Pallas kernel scores corpus tiles in VMEM and never
    materializes the [B, N] score block; elsewhere it falls back to the
    bounded-memory ``chunked_top_k`` scan (which now auto-pads ragged
    tails, so any ``n_items`` works).
    """
    from predictionio_tpu.ops.pallas_kernels import fused_topk

    q = _forward_users(params, user_ids)
    all_items = _forward_items(params, jnp.arange(n_items))
    if chunk:
        return fused_topk(q, all_items, k, chunk=chunk)
    return top_k_scores(q, all_items, k)
