"""First-order Markov chain over item transitions.

Reference: e2/src/main/scala/.../engine/MarkovChain.scala (SURVEY.md §2.1
"e2") — transition counts from observed state sequences, row-normalized,
top-K next-state prediction.  TPU shape: counts are one scatter-add on
device; prediction is a row gather + ``lax.top_k``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MarkovChainModel", "train_markov_chain", "predict_next"]


@dataclasses.dataclass
class MarkovChainModel:
    transition: jax.Array   # [S, S] row-stochastic (Laplace-smoothed)
    n_states: int


def train_markov_chain(
    prev_states: np.ndarray,
    next_states: np.ndarray,
    n_states: int,
    *,
    smoothing: float = 0.0,
) -> MarkovChainModel:
    """Estimate P(next | prev) from transition pairs."""
    prev_j = jnp.asarray(prev_states, jnp.int32)
    next_j = jnp.asarray(next_states, jnp.int32)

    @jax.jit
    def _counts(p, q):
        flat = p * n_states + q  # int32 is ample: S² < 2³¹ for any real S
        c = jnp.zeros((n_states * n_states,), jnp.float32)
        c = c.at[flat].add(1.0)
        return c.reshape(n_states, n_states)

    counts = _counts(prev_j, next_j) + smoothing
    row = counts.sum(axis=1, keepdims=True)
    transition = jnp.where(row > 0, counts / jnp.maximum(row, 1e-12), 0.0)
    return MarkovChainModel(transition=transition, n_states=n_states)


def predict_next(model: MarkovChainModel, states: jax.Array,
                 k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k next states per input state: ([B,k] probs, [B,k] ids)."""
    rows = model.transition[jnp.asarray(states)]
    return jax.lax.top_k(rows, k)
