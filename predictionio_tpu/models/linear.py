"""Softmax / logistic regression — full-batch L-BFGS-free training.

Reference: Spark MLlib logistic regression (gradient passes via
``treeAggregate``) behind the classification template (SURVEY.md §2.2).
TPU shape: the whole dataset lives on device (batch dim sharded over the
``data`` axis), each optimization step is one jitted fused
forward/backward; the hierarchical gradient reduction is XLA's ``psum``.
Optimizer: optax adam — converges to the same optimum as MLlib's LBFGS on
these convex problems.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import AXIS_DATA, put_sharded

__all__ = ["LogisticRegressionConfig", "LogisticRegressionModel", "train", "predict_proba"]


@dataclasses.dataclass
class LogisticRegressionConfig:
    n_classes: int
    reg: float = 0.0            # L2 (MLlib regParam)
    learning_rate: float = 0.1
    steps: int = 200
    seed: int = 0
    standardize: bool = True    # MLlib standardizes features by default


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["weights", "bias", "mean", "scale"], meta_fields=[])
@dataclasses.dataclass
class LogisticRegressionModel:
    weights: jax.Array   # [D, C]
    bias: jax.Array      # [C]
    mean: jax.Array      # [D] feature standardization
    scale: jax.Array     # [D]


def _loss(params, x, y_onehot, w_sample, reg):
    logits = x @ params["w"] + params["b"]
    ll = optax.softmax_cross_entropy(logits, y_onehot)
    data = jnp.sum(ll * w_sample) / jnp.maximum(jnp.sum(w_sample), 1.0)
    return data + reg * jnp.sum(params["w"] ** 2)


@functools.partial(jax.jit, static_argnames=("steps",), donate_argnums=())
def _fit(x, y_onehot, w_sample, w0, b0, reg, lr, steps: int):
    tx = optax.adam(lr)
    params = {"w": w0, "b": b0}
    opt_state = tx.init(params)

    def body(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(_loss)(params, x, y_onehot, w_sample, reg)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    (params, _), losses = jax.lax.scan(body, (params, opt_state), None,
                                       length=steps)
    return params, losses


def train(
    x: np.ndarray,
    y: np.ndarray,
    cfg: LogisticRegressionConfig,
    mesh: Optional[Mesh] = None,
    sample_weight: Optional[np.ndarray] = None,
) -> LogisticRegressionModel:
    x = np.asarray(x, np.float32)
    n, d = x.shape
    if cfg.standardize:
        mean = x.mean(axis=0)
        scale = x.std(axis=0)
        scale = np.where(scale < 1e-8, 1.0, scale)
    else:
        mean = np.zeros(d, np.float32)
        scale = np.ones(d, np.float32)
    xs = (x - mean) / scale
    y_onehot = jax.nn.one_hot(jnp.asarray(y), cfg.n_classes, dtype=jnp.float32)
    w_sample = jnp.asarray(
        sample_weight if sample_weight is not None else np.ones(n, np.float32))
    xj = jnp.asarray(xs)
    if mesh is not None:
        sh = NamedSharding(mesh, P(AXIS_DATA))
        xj = put_sharded(xj, mesh, sh)
        y_onehot = put_sharded(y_onehot, mesh, sh)
        w_sample = put_sharded(w_sample, mesh, sh)
    w0 = jnp.zeros((d, cfg.n_classes), jnp.float32)
    b0 = jnp.zeros((cfg.n_classes,), jnp.float32)
    params, _ = _fit(xj, y_onehot, w_sample, w0, b0,
                     jnp.float32(cfg.reg), jnp.float32(cfg.learning_rate),
                     cfg.steps)
    return LogisticRegressionModel(
        weights=params["w"], bias=params["b"],
        mean=jnp.asarray(mean), scale=jnp.asarray(scale))


@jax.jit
def predict_proba(model: LogisticRegressionModel, x: jax.Array) -> jax.Array:
    xs = (jnp.asarray(x, jnp.float32) - model.mean) / model.scale
    return jax.nn.softmax(xs @ model.weights + model.bias, axis=-1)
