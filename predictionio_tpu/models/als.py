"""Alternating least squares, TPU-shaped.

Reference behavior: Spark MLlib ``ALS.train`` / ``ALS.trainImplicit`` as
invoked by the recommendation template (SURVEY.md §2.2, §3.1 hot loop).
MLlib's implementation is shuffle-shaped: user×item factor blocks exchanged
between executors, per-block normal equations solved via JNI BLAS.

The TPU design replaces all of that with one batched XLA program per side
per iteration (SURVEY.md §7 step 5):

- ragged ratings → degree-bucketed padded blocks (host-side, once)
- per-entity normal equations built by batched einsum over gathered
  factors (MXU) — ``A_u = Σ_i w_ui · y_i y_iᵀ``
- batched Cholesky solves (``ops.linalg.batched_ridge_solve``)
- factor "exchange" = nothing within a chip, an all-gather across the mesh
  (factors replicated; solve rows sharded on the ``data`` axis)

Regularization follows MLlib's ALS-WR scaling: λ·n_u per user (n_u = that
user's rating count), λ·n_i per item.  Implicit feedback follows
Hu-Koren-Volinsky: confidence c = 1 + α·r, preference p = 1(r>0), with the
``YᵀY`` term shared across users.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import math
import threading
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.linalg import gram, masked_gram
from predictionio_tpu.ops.pallas_kernels import (
    fits_vmem,
    fused_gram_vector_pallas,
    gj_fits_vmem,
    pallas_supported,
    ridge_solve_gj_pallas,
    ridge_solve_lu_pallas,
)
from predictionio_tpu.ops.ragged import LEN_ALIGN, Padded, bucket_by_length
from predictionio_tpu.ops.topk import chunked_top_k, top_k_scores
from predictionio_tpu.parallel.mesh import AXIS_DATA, put_sharded

__all__ = ["ALSConfig", "ALSModel", "ALSInputs", "prepare_als_inputs",
           "train_als", "train_als_prepared", "recommend", "predict_scores",
           "fold_in"]


@dataclasses.dataclass
class ALSConfig:
    rank: int = 32
    iterations: int = 10
    reg: float = 0.01          # MLlib regParam (λ), ALS-WR scaled by degree
    alpha: float = 1.0         # implicit confidence scale
    implicit: bool = False
    max_degree: Optional[int] = None   # truncate overlong entities (None = exact)
    # "auto" fits bounds to the degree histogram (ops.ragged.fit_bounds,
    # DP-minimal padded slots, sublane-aligned); a tuple pins them.
    bucket_bounds: Union[Sequence[int], str] = "auto"
    # Zipf-head entities longer than this are split into partial rows and
    # their normal-equation pieces segment-summed — exact, and it removes
    # the dominant padding waste (measured 3.7x padded slots on the ML-1M
    # item side without it).  None disables splitting.
    split_above: Optional[int] = 4096
    seed: int = 42
    dtype: str = "float32"     # factor storage dtype; solves always f32
    # Gather + matmul input precision for the gram/rhs builds (factor
    # MASTER copies and all accumulation stay f32; only the gathered
    # operands are cast).  The v5e gather engine is row-rate limited
    # (~0.34 G rows/s f32, ~0.46 bf16 measured) and the training loop is
    # gather-bound at ML-25M, so "auto" = bfloat16 on TPU, float32
    # elsewhere (CPU tests keep numpy-oracle exactness).
    gram_dtype: str = "auto"
    # Normal-equation solver: "auto" = the Pallas shrinking-elimination
    # kernel ("lu") on TPU — the XLA batched Cholesky was the single
    # largest cost of an iteration and full Gauss-Jordan 1.4x slower
    # than LU — Cholesky elsewhere.  "cholesky"/"gj"/"lu" force a path.
    solver: str = "auto"
    use_pallas: Optional[bool] = None  # None = auto (on for single-chip TPU)
    # HBM guard: cap the gathered [rows, L, K] block at this many floats;
    # jumbo buckets are solved in row chunks.  Round 4 doubled the default
    # (1<<26 → 1<<27): the Pallas gram path gathers in bf16 with NO
    # relayout copy alongside, so the same byte budget admits twice the
    # rows — and halving the chunk count cuts both the cold compile time
    # (program size ∝ chunk count; no persistent compile cache on this
    # backend) and per-chunk dispatch overhead.  1 GB f32-equivalent
    # blocks OOMed the 16 GB chip at ML-25M scale; 512 MB-equivalent
    # (256 MB bf16 gathered) leaves headroom.
    max_block_floats: int = 1 << 27
    # "auto" = bucket on-device (ops/device_prep.py) when running on TPU
    # with no mesh and no max_degree truncation; True/False force.  The
    # host-numpy bucketing + padded-block upload was 84% of end-to-end
    # train wall time at ML-25M (round-2 verdict item 3); the device path
    # ships compact COO once and runs the layout transform as one XLA
    # program.
    device_prep: Union[bool, str] = "auto"
    # Factor placement on a mesh (SURVEY §2.4 row 2 — the blueprint's
    # blocked ALS).  "replicated" keeps both factor matrices whole on
    # every chip (cheapest at ML-25M rank 64: ~57 MB); "sharded"
    # row-shards the PERSISTENT factor state over the ``data`` axis so it
    # scales 1/n_chips — XLA inserts the per-sweep gathers (transient,
    # full-size) and re-shards the solve outputs, riding ICI; "auto"
    # shards once both matrices exceed ``factor_shard_threshold`` bytes.
    factor_sharding: str = "auto"
    factor_shard_threshold: int = 256 << 20
    # Windowed per-chunk gather for blocked mode (SURVEY §2.4 row 2 /
    # §7 "hard parts").  Sharding the PERSISTENT factors (above) still
    # left each sweep's TRANSIENT gather full-size: every chunk read the
    # whole other-side factor matrix (~51 GB at 100M users rank 128 —
    # past HBM).  Windowed mode gathers, per HBM chunk, ONLY the factor
    # rows that chunk's indices touch: prep computes the sorted unique
    # window + remaps the chunk indices to window-local, and the sweep
    # fetches the window from the sharded factors with a masked local
    # take + psum over the data axis (each row lives in exactly one
    # shard, so the sum is exact in f32) — transient ∝ chunk working
    # set (≤ max_block_floats/rank rows), not matrix size.  "auto" =
    # on whenever the factors are sharded; per-chunk it only engages
    # when the window is under half the matrix (else the plain gather
    # is smaller).  True/False force.
    gather_window: Union[bool, str] = "auto"


@dataclasses.dataclass
class ALSModel:
    """Trained factors. ``user_factors [U,K]``, ``item_factors [I,K]``."""

    user_factors: jax.Array
    item_factors: jax.Array
    rank: int
    implicit: bool

    def tree_flatten(self):  # manual pytree-ish helpers for checkpointing
        return {"user_factors": self.user_factors, "item_factors": self.item_factors}


def _init_factors(n_users: int, n_items: int, k: int, seed: int):
    """Deterministic scaled-normal factor init, identical on every backend.

    MLlib uses Xavier-ish normal / sqrt(k).  Both prep paths (host and
    device-side) MUST share this: jax.random's threefry bits are
    backend-deterministic, so the same ``ALSConfig.seed`` produces the same
    model whether prep ran on TPU, CPU, or a mesh (round-3 advisor finding:
    a numpy init here diverged from the device path's jax.random init,
    breaking mesh-vs-meshless equivalence on real TPU backends).
    """
    key = jax.random.PRNGKey(seed)
    ku, ki = jax.random.split(key)
    scale = np.sqrt(k).astype(np.float32)
    uf = jax.random.normal(ku, (n_users, k), jnp.float32) / scale
    itf = jax.random.normal(ki, (n_items, k), jnp.float32) / scale
    return uf, itf


def _shard_factors(config: ALSConfig, n_users: int, n_items: int) -> bool:
    """Whether a mesh run row-shards the persistent factor matrices."""
    if config.factor_sharding == "sharded":
        return True
    if config.factor_sharding == "replicated":
        return False
    if config.factor_sharding != "auto":
        raise ValueError(
            f"factor_sharding must be 'auto', 'replicated' or 'sharded' "
            f"(got {config.factor_sharding!r})")
    return (n_users + n_items) * config.rank * 4 > config.factor_shard_threshold


def _factor_constraint(arr: jax.Array) -> Optional[NamedSharding]:
    """The sharding to re-impose on factor state each sweep, if blocked."""
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.spec and sh.spec[0] == AXIS_DATA:
        return sh
    return None


def _resolve_gram_dtype(gram_dtype: str) -> str:
    """"auto" → bfloat16 on TPU (gather row-rate win), float32 elsewhere."""
    if gram_dtype == "auto":
        try:
            return "bfloat16" if jax.default_backend() == "tpu" else "float32"
        except Exception:
            return "float32"
    return gram_dtype


def _gram_pieces(
    indices: jax.Array,    # [R, L] int32 — other-side ids
    values: jax.Array,     # [R, L] f32
    mask: jax.Array,       # [R, L] bool
    factors: jax.Array,    # [N, K] other-side factors
    alpha: jax.Array,      # scalar α
    implicit: bool,
    use_pallas: bool,
    gram_dtype,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-row normal-equation pieces: A [R,K,K], b [R,K], degree [R]."""
    m = mask.astype(jnp.float32)
    if implicit:
        # Hu-Koren-Volinsky per MLlib: c = 1 + α·|r|, p = 1(r>0).
        # A = YᵀY + Σ (c-1)·y yᵀ,  b = Σ c·p·y — (c-1) ≥ 0 keeps A PSD.
        w = alpha * jnp.abs(values) * m       # c - 1
        cvec = (1.0 + w) * (values > 0).astype(jnp.float32) * m
    else:
        w = m
        cvec = values * m
    if use_pallas:
        # Gather in gram_dtype (bf16 on TPU: the v5e gather engine is
        # row-rate limited and bf16 halves the bytes) and feed the fused
        # kernel DIRECTLY — Pallas consumes the gather's natural K-minor
        # layout, so no relayout copy is emitted (the einsum path's dots
        # want L-minor and XLA copies the whole [R,L,K] block to get it:
        # 47.7 ms/iter at the ML-25M shape, round-3 phase profile).
        f = factors.astype(gram_dtype)[indices]   # [R, L, K] gather
        a, b = fused_gram_vector_pallas(f, w, cvec,
                                        interpret=not pallas_supported())
    else:
        # Gather in gram_dtype: the factor cast is [N, K] (cheap, one pass)
        # and the row-rate-limited gather then moves half the bytes in
        # bf16.  Single-temp formulation: fold sqrt(w) into the gathered
        # factors so only ONE [R, L, K] intermediate exists (the naive f
        # and f*w pair doubled peak HBM and OOMed the ML-25M shape).
        # Entries with cvec != 0 but w == 0 (implicit feedback with
        # alpha == 0) get an epsilon fold weight so the rhs survives the
        # division exactly; the epsilon perturbs A by ~1e-12 per entry —
        # far below the ridge.
        f = factors.astype(gram_dtype)[indices]   # [R, L, K] gather
        sw = jnp.sqrt(w + jnp.where(cvec != 0.0, 1e-12, 0.0))
        g = f * sw[..., None].astype(gram_dtype)
        a = jax.lax.dot_general(g, g, (((1,), (1,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32)
        s = (cvec / jnp.maximum(sw, 1e-30)).astype(gram_dtype)
        b = jnp.einsum("blk,bl->bk", g, s,
                       preferred_element_type=jnp.float32)
    return a, b, m.sum(axis=1)


def _solve_bucket(
    indices, values, mask, factors, yty, reg, alpha,
    implicit: bool, use_pallas: bool, gram_dtype, solver: str,
) -> jax.Array:
    """One padded block of normal equations + batched solves → [R, K]."""
    a, b, degree = _gram_pieces(indices, values, mask, factors, alpha,
                                implicit, use_pallas, gram_dtype)
    if implicit:
        a = yty[None, :, :] + a
    return _ridge(a, b, reg * jnp.maximum(degree, 1.0), solver)  # ALS-WR: λ·n_u


def _ridge(a: jax.Array, b: jax.Array, reg_vec: jax.Array,
           solver: str = "cholesky") -> jax.Array:
    """Batched SPD solve ``(A + diag(reg)) x = b``.

    ``gj`` = the Pallas Gauss-Jordan kernel — on v5e the XLA batched
    Cholesky path is the single largest cost of an ALS iteration (its
    K-step while-loop of small dynamic slices runs at ~10 GF/s), so the
    dense-VPU elimination wins despite ~9x the nominal FLOPs.
    """
    if solver == "lu":
        # Shrinking elimination: ~K^3/3 FLOPs vs GJ's ~K^3; measured 1.4x
        # faster at the full-scale solve count (23.5 vs 32.7 ms / 131k
        # rank-64 systems on v5e).
        return ridge_solve_lu_pallas(a, b, reg_vec,
                                     interpret=not pallas_supported())
    if solver == "gj":
        return ridge_solve_gj_pallas(a, b, reg_vec,
                                     interpret=not pallas_supported())
    k = a.shape[-1]
    eye = jnp.eye(k, dtype=a.dtype)
    a_reg = a + reg_vec[:, None, None] * eye
    chol = jnp.linalg.cholesky(a_reg)
    y = jax.scipy.linalg.solve_triangular(chol, b[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(chol, y, lower=True, trans="T")
    return x[..., 0]


def _scatter_rows(dst: jax.Array, row_ids: jax.Array, rows: jax.Array) -> jax.Array:
    """Write solved rows back; row_id == -1 rows (bucket padding) dropped.

    Invalid rows are routed out-of-bounds so ``mode="drop"`` discards them —
    never clamp them to a real index (a clamped duplicate write races the
    genuine row-0 update).
    """
    safe = jnp.where(row_ids >= 0, row_ids, dst.shape[0])
    return dst.at[safe].set(rows, mode="drop")


@functools.partial(jax.jit, static_argnames=(
    "implicit", "use_pallas", "gram_dtype", "solver"))
def _side_step(
    indices, values, mask, row_ids, dst_factors, src_factors, reg, alpha, *,
    implicit, use_pallas, gram_dtype="float32", solver="cholesky",
):
    yty = gram(src_factors) if implicit else jnp.zeros(
        (src_factors.shape[1], src_factors.shape[1]), jnp.float32)
    solved = _solve_bucket(indices, values, mask, src_factors, yty, reg, alpha,
                           implicit, use_pallas,
                           jnp.dtype(_resolve_gram_dtype(gram_dtype)), solver)
    return _scatter_rows(dst_factors, row_ids, solved)


def _merged_solve(
    indices, values, mask, seg_ids, ent_ids, dst_factors, src_factors, yty,
    reg, alpha, implicit, use_pallas, gram_dtype, solver,
):
    """Split-bucket step: partial rows → segment-summed normal equations.

    Over-long entities arrive as several partial rows (ops/ragged.py
    ``split_above``); their A/b/degree pieces are scatter-added per segment
    before the solve, so the result is bitwise the same math as an unsplit
    row without paying max-degree padding.  Shared by the fused training
    loop and the standalone jitted wrapper below.
    """
    a, b, deg = _gram_pieces(indices, values, mask, src_factors, alpha,
                             implicit, use_pallas, gram_dtype)
    n_seg = ent_ids.shape[0]
    k = src_factors.shape[1]
    A = jnp.zeros((n_seg, k, k), jnp.float32).at[seg_ids].add(a, mode="drop")
    B = jnp.zeros((n_seg, k), jnp.float32).at[seg_ids].add(b, mode="drop")
    degree = jnp.zeros((n_seg,), jnp.float32).at[seg_ids].add(deg, mode="drop")
    if implicit:
        A = yty[None, :, :] + A
    solved = _ridge(A, B, reg * jnp.maximum(degree, 1.0), solver)
    return _scatter_rows(dst_factors, ent_ids, solved)


@functools.partial(jax.jit, static_argnames=(
    "implicit", "use_pallas", "gram_dtype", "solver"))
def _merged_side_step(
    indices, values, mask, seg_ids, ent_ids, dst_factors, src_factors,
    reg, alpha, *, implicit, use_pallas, gram_dtype="float32",
    solver="cholesky",
):
    yty = gram(src_factors) if implicit else jnp.zeros(
        (src_factors.shape[1], src_factors.shape[1]), jnp.float32)
    return _merged_solve(indices, values, mask, seg_ids, ent_ids,
                         dst_factors, src_factors, yty, reg, alpha,
                         implicit, use_pallas,
                         jnp.dtype(_resolve_gram_dtype(gram_dtype)), solver)


def _window_gather(src: jax.Array, win: jax.Array,
                   sharding: Optional[NamedSharding]) -> jax.Array:
    """Fetch factor rows ``win`` from (possibly row-sharded) ``src``.

    Sharded case: masked local take + ``psum`` over the data axis via
    ``shard_map`` — each requested row lives in exactly ONE shard, so
    every other shard contributes exact zeros and the f32 sum is
    bitwise the row value.  The transient this materializes is
    ``[len(win), K]`` (the chunk's working set); relying on GSPMD's own
    gather lowering here is exactly what re-materialized the full
    matrix per sweep in round 4.
    """
    if sharding is None:
        return src[win]
    from predictionio_tpu.parallel.compat import shard_map

    mesh = sharding.mesh
    d = mesh.shape[AXIS_DATA]
    shard_rows = src.shape[0] // d  # blocked mode pads rows to divide

    def local(src_local, win_rep):
        lo = jax.lax.axis_index(AXIS_DATA) * shard_rows
        loc = win_rep - lo
        ok = (loc >= 0) & (loc < shard_rows)
        rows = jnp.where(ok[:, None],
                         src_local[jnp.where(ok, loc, 0)], 0.0)
        return jax.lax.psum(rows, AXIS_DATA)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(AXIS_DATA, None), P()),
                     out_specs=P())(src, win)


def _chunk_window(idx: np.ndarray, msk: np.ndarray, n_src: int,
                  pad_to: int = 64) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Sorted unique src ids a chunk touches + the window-local remap.

    Returns ``None`` when windowing would not shrink the gather (window
    ≥ half the matrix) — the caller keeps the plain full-matrix path.
    Padding repeats the LAST (max) id so the array stays sorted for
    ``searchsorted``; duplicate fetches of one row are harmless.
    """
    win = np.unique(idx[msk])
    if win.size == 0:
        win = np.zeros(1, idx.dtype)
    padded = -(-win.size // pad_to) * pad_to
    if padded >= n_src // 2:
        return None
    win = np.pad(win, (0, padded - win.size), mode="edge")
    local = np.searchsorted(win, idx).astype(np.int32)
    local[~msk] = 0
    return win.astype(np.int32), local


def _chunk_split_bucket(
    p: Padded, rank: int, max_block_floats: int, pad_rows: int,
) -> List[Tuple]:
    """Cut a split bucket into HBM-bounded chunks at ENTITY boundaries.

    All partial rows of one entity must land in the same dispatch (their
    normal-equation pieces segment-sum before the solve), and ragged.py
    lays partial rows out grouped by entity, so cutting between entities
    is always legal.  Each chunk gets re-based seg_ids and its own ent_ids
    slice.
    """
    r, l = p.indices.shape
    rows_max = max(pad_rows, (max_block_floats // max(l * rank, 1))
                   // pad_rows * pad_rows)
    if r <= rows_max:
        return [(p.indices, p.values, p.mask, p.seg_ids, p.ent_ids)]
    n_seg = len(p.ent_ids)
    # First partial row of each segment (segments are contiguous row runs).
    seg_starts = np.searchsorted(p.seg_ids, np.arange(n_seg + 1), side="left")
    chunks = []
    e0 = 0
    while e0 < n_seg:
        e1 = e0 + 1
        while e1 < n_seg and seg_starts[e1 + 1] - seg_starts[e0] <= rows_max:
            e1 += 1
        r0, r1 = int(seg_starts[e0]), int(seg_starts[e1])
        if r1 == r0:  # trailing padding-only segments
            break
        rows = slice(r0, r1)
        seg = p.seg_ids[rows] - e0
        n_seg_chunk = e1 - e0
        # Row/segment padding to the mesh granule.
        row_pad = (-(r1 - r0)) % pad_rows
        seg_pad = (-n_seg_chunk) % pad_rows
        idx = np.pad(p.indices[rows], ((0, row_pad), (0, 0)))
        vals = np.pad(p.values[rows], ((0, row_pad), (0, 0)))
        msk = np.pad(p.mask[rows], ((0, row_pad), (0, 0)))
        seg = np.pad(seg, (0, row_pad),
                     constant_values=n_seg_chunk + seg_pad)  # OOB → dropped
        ent = np.pad(p.ent_ids[e0:e1], (0, seg_pad), constant_values=-1)
        chunks.append((idx, vals, msk, seg.astype(np.int32), ent))
        e0 = e1
    return chunks


def _device_buckets(
    buckets: List[Padded],
    mesh: Optional[Mesh],
    rank: int,
    max_block_floats: int,
    pad_rows: int,
    window_n_src: Optional[int] = None,
) -> List[Tuple]:
    """Transfer padded buckets, splitting any whose gathered [R, L, K]
    block would exceed the HBM budget into fixed-shape row chunks (last
    chunk row-padded with row_id = -1, which the scatter drops).

    Returns ``("plain", idx, vals, msk, row_ids)`` or
    ``("merged", idx, vals, msk, seg_ids, ent_ids)`` tuples.  With
    ``window_n_src`` (blocked factor-sharded mode), chunks whose src
    working set is under half the matrix become ``("plain_w", ...,
    win)`` / ``("merged_w", ..., win)``: indices are window-local and
    ``win`` (replicated) names the factor rows the sweep must fetch.

    ISSUE 13 satellite (carried since PR 5): the staging rides the
    SHARED input path — a :class:`~predictionio_tpu.data.prefetch.
    DevicePrefetcher` whose source generator does the host-side
    chunk/pad/window work and whose put function issues the transfers,
    so (a) the next chunk's numpy padding overlaps the previous chunk's
    asynchronously-draining H2D instead of serializing after it, and
    (b) ALS staging shows up in the same ``pio_prefetch_*`` metrics and
    train-loop lints that already cover the deep models, instead of its
    own private path.
    """

    def windowed(kind, idx, msk, rest):
        if window_n_src is None:
            return kind, (idx, *rest), None
        w = _chunk_window(idx, msk, window_n_src)
        if w is None:
            return kind, (idx, *rest), None
        win, local = w
        return kind, (local, *rest), win

    def entries():
        """(kind, host_arrs, win) stream — all chunk/pad/window numpy
        work happens HERE, i.e. on the prefetcher's prep thread."""
        for p in buckets:
            if p.split:
                for idx, vals, msk, seg, ent in _chunk_split_bucket(
                        p, rank, max_block_floats, pad_rows):
                    yield windowed("merged", idx, msk,
                                   (vals, msk, seg, ent))
                continue
            r, l = p.indices.shape
            rows_max = max(pad_rows,
                           (max_block_floats // max(l * rank, 1))
                           // pad_rows * pad_rows)
            chunks = [(p.indices, p.values, p.mask, p.row_ids)] \
                if r <= rows_max else []
            if r > rows_max:
                for start in range(0, r, rows_max):
                    sl = slice(start, start + rows_max)
                    idx, vals = p.indices[sl], p.values[sl]
                    msk, rid = p.mask[sl], p.row_ids[sl]
                    short = rows_max - idx.shape[0]
                    if short:
                        idx = np.pad(idx, ((0, short), (0, 0)))
                        vals = np.pad(vals, ((0, short), (0, 0)))
                        msk = np.pad(msk, ((0, short), (0, 0)))
                        rid = np.pad(rid, (0, short), constant_values=-1)
                    chunks.append((idx, vals, msk, rid))
            for idx, vals, msk, rid in chunks:
                yield windowed("plain", idx, msk, (vals, msk, rid))

    def put_entry(entry):
        kind, host_arrs, win = entry
        if mesh is not None:
            # put_sharded takes the HOST arrays directly — a jnp.asarray
            # first would waste a full default-device upload (+ download
            # in a multi-host gang).
            row = NamedSharding(mesh, P(AXIS_DATA))
            arrs = [put_sharded(a, mesh, row) for a in host_arrs]
            if win is not None:
                arrs.append(put_sharded(win, mesh,
                                        NamedSharding(mesh, P())))
        else:
            arrs = [jnp.asarray(a) for a in host_arrs]
            if win is not None:
                arrs.append(jnp.asarray(win))
        return (kind + "_w" if win is not None else kind, *arrs)

    from predictionio_tpu.data.prefetch import DevicePrefetcher

    out = []
    with DevicePrefetcher(entries(), prep_fn=lambda e: e,
                          put_fn=put_entry, count_fn=lambda e: 1,
                          model="als") as pf:
        for batch in pf:
            out.append(batch.args)
    return out


@dataclasses.dataclass
class ALSInputs:
    """Device-resident padded buckets + factor init (prep done once).

    Separating prep from the iteration loop lets callers (serving reloads,
    the benchmark's slope timing, incremental retrains) re-run the fused
    training program without re-bucketing or re-uploading.

    Two layouts: the host/mesh path stores PRE-CHUNKED tuples
    (``chunk_specs is None``); the device-prep path stores BUCKET-level
    arrays plus static ``chunk_specs`` and the training loop slices the
    HBM chunks in-graph — emitting per-chunk outputs from the build
    program cost ~1.1 s of (serialized, uncacheable) compile per chunk on
    this backend, ~45 s of the round-3 cold start.
    """

    uf0: jax.Array
    itf0: jax.Array
    user_buckets: List[Tuple]
    item_buckets: List[Tuple]
    n_users: int
    n_items: int
    # Per side: tuple over buckets of ("plain", ((cs, cn), ...)) or
    # ("merged", pad_to, ((e0, e1, r0, r1), ...)); None = pre-chunked.
    chunk_specs: Optional[Tuple[Tuple, Tuple]] = None
    # Future resolving to (statics, compiled loop executable) from the
    # plan-shape pre-warm, or None; loop_warm_statics mirrors the statics
    # the pre-warm lowered so a mismatched train can skip the wait.
    loop_warm: Optional[object] = None
    loop_warm_statics: Optional[dict] = None


def prepare_als_inputs(
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    ratings: Optional[np.ndarray],
    n_users: int,
    n_items: int,
    config: ALSConfig,
    mesh: Optional[Mesh] = None,
    host_ids: Optional[Tuple[np.ndarray, np.ndarray]] = None,
) -> ALSInputs:
    """Bucketing + transfer for :func:`train_als_prepared`.

    Dispatches to the device-side layout transform
    (:mod:`predictionio_tpu.ops.device_prep`) on TPU — compact COO up,
    one XLA program builds the padded blocks in HBM — and to the
    host-numpy path elsewhere (CPU tests, meshes, max_degree truncation).
    ``host_ids``: optional numpy copies of (user_ids, item_ids) for
    callers that pass pre-uploaded device arrays — lets the bucket plan
    run on host (one bincount) instead of per-op device round-trips.
    """
    use_dev = config.device_prep
    if use_dev == "auto":
        try:
            use_dev = (jax.default_backend() == "tpu" and mesh is None
                       and config.max_degree is None)
        except Exception:
            use_dev = False
    if use_dev:
        return _prepare_als_inputs_device(user_ids, item_ids, ratings,
                                          n_users, n_items, config,
                                          host_ids=host_ids)
    k = config.rank
    # Row counts pad to the lcm of the mesh axis (sharded dims must
    # divide) and the TPU sublane (LEN_ALIGN): unaligned bucket rows made
    # XLA pad/relayout every gathered [R, L, K] block in-graph, EVERY
    # iteration — measured 292 vs 177 ms/iter at the ML-25M shape, ~70 ms
    # of it pad/misc ops (the device-prep plan has always 8-aligned its
    # rows; this brings the host/mesh layout into lock-step).
    d = mesh.shape[AXIS_DATA] if mesh is not None else 1
    pad_rows = math.lcm(LEN_ALIGN, d)
    uf, itf = _init_factors(n_users, n_items, k, config.seed)
    sharded = mesh is not None and _shard_factors(config, n_users, n_items)
    window = config.gather_window
    if window == "auto":
        # A 1-device "mesh" has no cross-shard transient to shrink — the
        # window only adds a second gather level (measured ~3% per-iter
        # on the real chip: 288 vs 280 ms).  Windows pay off from 2
        # shards up, where they bound the transient (BASELINE.md).
        window = sharded and d > 1
    elif not isinstance(window, bool):
        raise ValueError(f"gather_window must be 'auto', True or False "
                         f"(got {config.gather_window!r})")
    window = window and sharded  # windows only exist over sharded factors
    if mesh is not None:
        if sharded:
            # Row-shard the persistent state; rows pad to the axis size
            # (sharded dims must divide).  Padded rows are never gathered
            # (indices < n) nor scattered to (row_ids < n); the final
            # model slices them off (train_als_prepared).
            uf = jnp.pad(uf, ((0, (-n_users) % d), (0, 0)))
            itf = jnp.pad(itf, ((0, (-n_items) % d), (0, 0)))
            spec = P(AXIS_DATA, None)
        else:
            spec = P()
        uf = put_sharded(uf, mesh, NamedSharding(mesh, spec))
        itf = put_sharded(itf, mesh, NamedSharding(mesh, spec))

    user_buckets = _device_buckets(
        bucket_by_length(user_ids, item_ids, ratings, n_users,
                         bucket_bounds=config.bucket_bounds,
                         max_len=config.max_degree, pad_rows_to=pad_rows,
                         split_above=config.split_above),
        mesh, k, config.max_block_floats, pad_rows,
        window_n_src=n_items if window else None,
    )
    item_buckets = _device_buckets(
        bucket_by_length(item_ids, user_ids, ratings, n_items,
                         bucket_bounds=config.bucket_bounds,
                         max_len=config.max_degree, pad_rows_to=pad_rows,
                         split_above=config.split_above),
        mesh, k, config.max_block_floats, pad_rows,
        window_n_src=n_users if window else None,
    )
    return ALSInputs(uf0=uf, itf0=itf, user_buckets=user_buckets,
                     item_buckets=item_buckets, n_users=n_users,
                     n_items=n_items)


# (BucketPlan, nnz) -> AOT-compiled build program.  LRU-bounded: a
# long-lived retrain loop sees a new nnz every cycle and must not leak one
# executable per retrain.
_BUILD_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_BUILD_CACHE_MAX = 6


def _build_cache_get(key):
    co = _BUILD_CACHE.get(key)
    if co is not None:
        _BUILD_CACHE.move_to_end(key)
    return co


def _build_cache_put(key, co):
    _BUILD_CACHE[key] = co
    while len(_BUILD_CACHE) > _BUILD_CACHE_MAX:
        _BUILD_CACHE.popitem(last=False)



# warm_key -> Future[(statics, loop executable) | None]; LRU-bounded like
# the build cache (retrain loops see a new plan every data refresh).
_WARM_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def _warm_cache_get(key):
    fut = _WARM_CACHE.get(key)
    if fut is not None:
        _WARM_CACHE.move_to_end(key)
    return fut


def _warm_cache_put(key, fut):
    _WARM_CACHE[key] = fut
    while len(_WARM_CACHE) > _BUILD_CACHE_MAX:
        _WARM_CACHE.popitem(last=False)


def _compile_build(lowered):
    """Compile a prep build program at REDUCED optimization effort.

    The build runs once per dataset (~5 s exec) but its default-effort
    compile was the cold-start wall (33 + 48 s for the two sides at the
    ML-25M shape).  ``exec_time_optimization_effort=-1`` compiles the
    same program in ~21 + 31 s with no measurable exec regression (the
    program is scatter/gather-bound; there's nothing for the scheduler
    to win).  The hot training loop stays at DEFAULT effort — low effort
    there measured 533 vs 184 ms/iter.  Falls back silently where the
    backend rejects the options (older libtpu, non-TPU platforms).
    """
    try:
        return lowered.compile(compiler_options={
            "exec_time_optimization_effort": -1.0,
            "memory_fitting_effort": -1.0,
        })
    except Exception:
        return lowered.compile()


def _plan_side(rows: jax.Array, n_rows: int, config: ALSConfig,
               host_rows: Optional[np.ndarray] = None):
    """One side's :class:`~ops.device_prep.BucketPlan` from COO ids.

    With ``host_rows`` (the caller's numpy copy of the same ids) the
    degree statistics run as one ``np.bincount`` — ~0.3 s at 25M rows.
    The device fallback exists for device-only callers, but each of its
    small jitted stats ops pays a compile + dispatch round-trip through
    the remote-TPU tunnel: 37.6 s measured for both sides at the ML-25M
    shape, which single-handedly blew the cold-prep budget.
    """
    from predictionio_tpu.ops.device_prep import (
        degree_histogram, plan_buckets,
    )

    split_above = config.split_above or 1 << 20
    if host_rows is not None:
        # Exact replica of ops.device_prep.degree_histogram: counts over
        # ALL n_rows entities (zero-degree included), degrees clipped at
        # the cap into cap+1 bins, over-cap degrees in entity-id order.
        # Match the device scatter-add's index semantics exactly: JAX
        # ``.at[rows].add`` WRAPS negative ids numpy-style (id + n_rows
        # for -n_rows <= id < 0) and drops ids outside [-n_rows, n_rows).
        host_rows = np.asarray(host_rows)
        if host_rows.size and host_rows.min() < 0:
            host_rows = np.where(host_rows < 0, host_rows + n_rows,
                                 host_rows)
        in_range = (host_rows >= 0) & (host_rows < n_rows)
        if not in_range.all():
            host_rows = host_rows[in_range]
        counts = np.bincount(host_rows, minlength=n_rows)
        hist = np.bincount(np.minimum(counts, split_above),
                           minlength=split_above + 1)
        over = counts > split_above
        n_over = int(over.sum())
        n_part = int(((counts[over] + split_above - 1)
                      // split_above).sum())
        over_deg = counts[over].astype(np.int64) if n_over else None
    else:
        counts = jnp.zeros(n_rows, jnp.int32).at[rows].add(1)
        hist, n_over, n_part = degree_histogram(counts, split_above)
        over_deg = None
        if n_over:
            # Degrees of the over-cap entities in id order — the plan
            # needs them to place split-chunk boundaries (tiny D2H).
            ids = jnp.nonzero(counts > split_above, size=n_over)[0]
            over_deg = np.asarray(counts[ids])
    return plan_buckets(hist, n_over, n_part, n_rows,
                        split_above=split_above,
                        bucket_bounds=config.bucket_bounds,
                        max_block_floats=config.max_block_floats,
                        rank=config.rank, over_degrees=over_deg)


def _plan_bucket_shapes(plan):
    """ShapeDtypeStruct bucket tuples exactly as the prep path emits them.

    Mirrors ``_prepare_als_inputs_device.one_side``: plain buckets at
    BUCKET level (one entry per plan bucket, chunk slicing is in-graph),
    then the merged split bucket.  Keeping this in lock-step with
    ``ops.device_prep.build_buckets`` is what lets the loop pre-warm
    lower an IDENTICAL program from shapes alone (test-asserted:
    tests/test_device_prep.py::TestPlanShapeLockstep).
    """
    S = jax.ShapeDtypeStruct
    f32, i32, b_ = jnp.float32, jnp.int32, jnp.bool_
    out = []
    for b, rp in zip(plan.bounds, plan.rows_padded):
        out.append(("plain", S((rp, b), i32), S((rp, b), f32),
                    S((rp, b), b_), S((rp,), i32)))
    specs = [("plain", ch) for ch in plan.plain_chunks]
    if plan.split_len is not None:
        pr, sl, ns = plan.split_rows, plan.split_len, plan.split_segs
        out.append(("merged", S((pr, sl), i32), S((pr, sl), f32),
                    S((pr, sl), b_), S((pr,), i32), S((ns,), i32)))
        specs.append(("merged", plan.pad_rows_to, plan.split_chunks))
    return out, tuple(specs)


def _lower_train_loop_from_plans(config: ALSConfig, plan_u, plan_i,
                                 n_users: int, n_items: int):
    """Lower the fused loop from plan shapes only → (statics, Lowered).

    The loop program depends only on the bucket LAYOUT (plan + rank) —
    verified identical HLO to the live call's lowering, real-array
    layouts included — so it can be lowered before prep outputs exist.
    Lowering runs on the CALLING thread (it holds the GIL; doing it on
    the warm thread stretched a concurrent warm re-prep 5.9 → 18 s).
    """
    ub, spec_u = _plan_bucket_shapes(plan_u)
    ib, spec_i = _plan_bucket_shapes(plan_i)
    statics = _resolve_loop_statics(config, ub, ib, (spec_u, spec_i))
    S = jax.ShapeDtypeStruct
    k = config.rank
    lowered = _train_loop.lower(
        S((n_users, k), jnp.float32), S((n_items, k), jnp.float32),
        tuple(tuple(b[1:]) for b in ub),
        tuple(tuple(b[1:]) for b in ib),
        S((), jnp.float32), S((), jnp.float32), S((), jnp.int32),
        factor_shardings=(None, None), **statics)
    return statics, lowered


def _compile_train_loop(statics, lowered, fut) -> None:
    """Warm-thread tail: pure compile RPC, no GIL-heavy work.

    Delivers ``(statics, executable)`` (or ``None`` on failure) through
    ``fut``; :func:`train_als_prepared` CALLS the executable directly —
    no reliance on any compile-cache or in-flight dedupe behavior of the
    backend (the shared tunnel's compile service proved too variable to
    reason about).
    """
    try:
        fut.set_result((statics, lowered.compile()))
    except Exception:  # pre-warm must never sink a train
        logging.getLogger(__name__).debug("loop pre-warm compile failed",
                                          exc_info=True)
        fut.set_result(None)


def _prepare_als_inputs_device(
    user_ids, item_ids, ratings, n_users: int, n_items: int,
    config: ALSConfig, host_ids=None,
) -> ALSInputs:
    """Device-side prep: COO up once, layout transform on the chip."""
    from predictionio_tpu.ops.device_prep import build_buckets

    k = config.rank
    # The DEVICE data always comes from user_ids/item_ids — host_ids is a
    # stats-only hint; feeding it to jnp.asarray would re-upload the COO
    # a second time when the caller already device_put it.  Numpy inputs
    # convert to int32 ONCE and serve both the upload and the host stats.
    def one_input(ids, hint):
        if hint is not None:
            return np.asarray(hint, dtype=np.int32), jnp.asarray(
                ids, dtype=jnp.int32)
        if isinstance(ids, np.ndarray):
            h = np.asarray(ids, dtype=np.int32)
            return h, jnp.asarray(h)
        return None, jnp.asarray(ids, dtype=jnp.int32)

    host_u, rows_u = one_input(user_ids,
                               host_ids[0] if host_ids else None)
    host_i, rows_i = one_input(item_ids,
                               host_ids[1] if host_ids else None)
    if ratings is None:
        vals = jnp.ones(rows_u.shape[0], jnp.float32)
    else:
        vals = jnp.asarray(ratings, dtype=jnp.float32)

    plan_u = _plan_side(rows_u, n_users, config, host_rows=host_u)
    plan_i = _plan_side(rows_i, n_items, config, host_rows=host_i)

    # The build program emits BUCKET-level arrays (chunk slicing happens
    # in-graph inside the training loop — see _expand_chunks); its compile
    # is the cold-start wall on this backend (serialized, uncacheable), so
    # every op it doesn't contain is ~1 s saved.  BOTH sides compile as
    # ONE program: the backend's compile service serializes separate
    # requests (user+item measured 50-77 s as a pair at the ML-25M shape)
    # while the merged program compiles in 38 s at the same low effort,
    # with identical exec time.  AOT executables bypass the jit cache, so
    # memoize per (plans, nnz) — warm re-preps (retrains, the bench's
    # second pass) skip the compile.  The factor init runs while the
    # build compiles (compilation is server-side; the device is free).
    import concurrent.futures

    build_u = dataclasses.replace(plan_u, plain_chunks=(), split_chunks=())
    build_i = dataclasses.replace(plan_i, plain_chunks=(), split_chunks=())

    def build_both(ru, ri, v, *, pu, pi):
        return (build_buckets.__wrapped__(ru, ri, v, pu),
                build_buckets.__wrapped__(ri, ru, v, pi))

    nnz = rows_u.shape[0]
    co = _build_cache_get((build_u, build_i, nnz))
    pend = None
    if co is None:
        lowered = jax.jit(build_both, static_argnames=("pu", "pi")).lower(
            rows_u, rows_i, vals, pu=build_u, pi=build_i)
        # Daemon thread + Future (same pattern as _compile_train_loop): a
        # non-daemon executor worker would block interpreter exit if the
        # backend compile RPC ever hangs.
        pend = concurrent.futures.Future()

        def _run_build_compile(lowered=lowered, fut=pend):
            try:
                fut.set_result(_compile_build(lowered))
            except BaseException as e:  # delivered to the waiter
                fut.set_exception(e)

        threading.Thread(target=_run_build_compile, daemon=True).start()

    # Fire the fused-loop compile from plan-derived shapes — its ~75 s
    # cold compile overlaps prep execution and whatever the caller does
    # before training, and the resulting EXECUTABLE is handed to
    # train_als_prepared through the future.  Submitted AFTER the build
    # compile so the (~2-worker, serializing) compile service finishes
    # the build first: loop-first measured prep_cold 81 s vs ~45 s this
    # way.  LRU'd so warm re-preps (retrains, the bench's second pass)
    # reuse the executable instead of re-lowering.
    # Key on exactly what the lowering consumes (plans + dims + the
    # statics-determining config fields): keying on the whole config made
    # a seed sweep recompile a byte-identical program per seed.
    warm_key = (plan_u, plan_i, n_users, n_items, config.rank,
                config.implicit, _resolve_gram_dtype(config.gram_dtype),
                config.solver, config.use_pallas)
    cached = _warm_cache_get(warm_key)
    if cached is not None and cached[0].done() \
            and cached[0].result() is None:
        cached = None  # failed pre-warm: retry rather than stay poisoned
    if cached is None:
        fut = concurrent.futures.Future()
        loop_statics = None
        try:
            loop_statics, loop_lowered = _lower_train_loop_from_plans(
                config, plan_u, plan_i, n_users, n_items)
            threading.Thread(target=_compile_train_loop,
                             args=(loop_statics, loop_lowered, fut),
                             daemon=True).start()
        except Exception:
            logging.getLogger(__name__).debug("loop pre-warm lower failed",
                                              exc_info=True)
            fut.set_result(None)
        # Statics stored ALONGSIDE the future so a train with different
        # statics can skip the wait without blocking on a compile it
        # would discard.
        cached = (fut, loop_statics)
        _warm_cache_put(warm_key, cached)
    fut, warm_statics = cached

    uf, itf = _init_factors(n_users, n_items, k, config.seed)

    if pend is not None:
        co = pend.result()
        _build_cache_put((build_u, build_i, nnz), co)

    side_u, side_i = co(rows_u, rows_i, vals)

    def one_side(built, plan):
        plain, split = built
        out = [("plain", *b) for b in plain]
        specs = [("plain", ch) for ch in plan.plain_chunks]
        if split is not None:
            out.extend(("merged", *b) for b in split)
            specs.append(("merged", plan.pad_rows_to, plan.split_chunks))
        return out, tuple(specs)

    user_buckets, spec_u = one_side(side_u, plan_u)
    item_buckets, spec_i = one_side(side_i, plan_i)
    return ALSInputs(uf0=uf, itf0=itf, user_buckets=user_buckets,
                     item_buckets=item_buckets, n_users=n_users,
                     n_items=n_items, chunk_specs=(spec_u, spec_i),
                     loop_warm=fut, loop_warm_statics=warm_statics)


def train_als(
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    ratings: Optional[np.ndarray],
    n_users: int,
    n_items: int,
    config: ALSConfig,
    mesh: Optional[Mesh] = None,
    *,
    checkpoint_dir=None,
    save_every: int = 0,
) -> ALSModel:
    """Train from COO triplets.

    With a mesh, solve rows are sharded over the ``data`` axis and factors
    are replicated — the per-iteration factor exchange is the implicit
    all-gather XLA inserts, riding ICI (reference: Spark shuffle between
    in/out ALS blocks).
    """
    inputs = prepare_als_inputs(user_ids, item_ids, ratings, n_users,
                                n_items, config, mesh)
    return train_als_prepared(inputs, config, checkpoint_dir=checkpoint_dir,
                              save_every=save_every)


def train_als_prepared(inputs: ALSInputs, config: ALSConfig, *,
                       checkpoint_dir=None, save_every: int = 0) -> ALSModel:
    """The fused iteration loop over pre-built device buckets.

    With ``checkpoint_dir`` + ``save_every``, the fori_loop is chunked at
    sweep granularity and factor state orbax-saved every ``save_every``
    sweeps; a killed train resumes from the latest complete sweep and —
    because the loop bound is a traced scalar (one compiled program
    regardless of chunking) and sweep math is index-independent — the
    resumed result is bitwise equal to an uninterrupted run
    (SURVEY.md §5.4: resume is a capability the reference lacks;
    tests/test_checkpoint_resume.py pins the equality).

    Supervision (resilience/supervision.py): each sweep chunk's factors
    are finiteness-checked before they can be checkpointed — a
    non-finite chunk rolls back to the last-good sweep (bounded by
    ``PIO_DIVERGENCE_RETRIES``, then ``TrainDiverged``); SIGTERM
    preemption force-saves the current sweep and raises
    ``TrainPreempted``; ``PIO_STEP_TIMEOUT_S`` arms a watchdog around
    every device dispatch (one ``sweeps()`` call).
    """
    k = config.rank
    uf, itf = inputs.uf0, inputs.itf0
    user_buckets = inputs.user_buckets
    item_buckets = inputs.item_buckets
    reg = jnp.float32(config.reg)
    alpha = jnp.float32(config.alpha)
    statics = _resolve_loop_statics(config, user_buckets, item_buckets,
                                    inputs.chunk_specs)
    # The WHOLE alternation loop is one jitted program: a fori_loop over
    # iterations with every bucket step unrolled in the body.  One dispatch
    # per training run instead of O(iterations x buckets) — launch/host
    # round-trip latency, not FLOPs, dominated the per-step formulation
    # (measured: solver/precision/padding changes moved ML-1M train time
    # <10%; fusing the loop is what actually buys throughput).
    ubk = tuple(tuple(b[1:]) for b in user_buckets)
    ibk = tuple(tuple(b[1:]) for b in item_buckets)

    # Blocked (factor-sharded) mode: re-impose the row-sharding on the
    # carry each sweep so GSPMD keeps the persistent state sharded instead
    # of silently replicating it after the scatter.
    factor_shardings = (_factor_constraint(uf), _factor_constraint(itf))

    # Use the pre-warm's executable when it compiled EXACTLY this program
    # (same statics, meshless): the train then waits on the overlapped
    # compile instead of issuing its own — immune to whatever caching or
    # queueing the backend's compile service does.
    warm_exe = None
    if (inputs.loop_warm is not None and factor_shardings == (None, None)
            and inputs.loop_warm_statics == statics):
        warm = inputs.loop_warm.result()  # blocks only while still compiling
        if warm is not None and warm[0] == statics:
            warm_exe = warm[1]

    def sweeps(uf, itf, n):
        if warm_exe is not None:
            return warm_exe(uf, itf, ubk, ibk, reg, alpha, jnp.int32(n))
        return _train_loop(
            uf, itf, ubk, ibk, reg, alpha, jnp.int32(n),
            factor_shardings=factor_shardings, **statics)

    from predictionio_tpu.resilience.supervision import (
        DivergenceGuard,
        RollbackRequested,
        StepWatchdog,
        TrainDiverged,
        TrainPreempted,
        all_finite,
        preemption_requested,
    )

    guard = DivergenceGuard("als")
    if checkpoint_dir and save_every > 0:
        from predictionio_tpu.workflow.checkpoint import TrainCheckpointer

        # Fingerprint = config + data dims: checkpoints from a different
        # config or a grown dataset are discarded, not resumed into.
        fp = f"als|{config}|{inputs.n_users}x{inputs.n_items}"
        ckpt = TrainCheckpointer(checkpoint_dir, save_every=save_every,
                                 fingerprint=fp)
        watchdog = StepWatchdog("als", checkpoint_fn=ckpt.flush)
        try:
            done = ckpt.restore_step((uf, itf), total_steps=config.iterations)
            if ckpt.restored_state is not None:
                uf, itf = ckpt.restored_state
            while done < config.iterations:
                n = min(save_every, config.iterations - done)
                watchdog.arm(done + n)
                uf2, itf2 = sweeps(uf, itf, n)
                finite = all_finite((uf2, itf2))  # forces the dispatch
                watchdog.disarm()
                if not finite:
                    # Rollback IN PLACE: re-restore the latest durable
                    # sweep (or the factor init when none exists) and
                    # replay.  The sweep math is index-independent, so a
                    # replayed chunk is the same program.  diverged()
                    # raises TrainDiverged once the retries are spent.
                    try:
                        guard.diverged(done + n, "non-finite factors")
                    except RollbackRequested:
                        pass
                    ckpt.restored_state = None
                    done = ckpt.restore_step((uf, itf),
                                             total_steps=config.iterations)
                    if ckpt.restored_state is not None:
                        uf, itf = ckpt.restored_state
                    else:
                        uf, itf = inputs.uf0, inputs.itf0
                        done = 0
                    continue
                uf, itf = uf2, itf2
                done += n
                saved = ckpt.maybe_save(done, (uf, itf))
                if preemption_requested():
                    if not saved:
                        ckpt.save(done, (uf, itf))
                    ckpt.flush()
                    raise TrainPreempted("als", done, True)
            ckpt.complete()
        finally:
            watchdog.stop()
            ckpt.close()
    else:
        watchdog = StepWatchdog("als")
        watchdog.arm(int(config.iterations))
        try:
            uf, itf = sweeps(uf, itf, config.iterations)
            # No checkpoint to roll back to: a non-finite result is a
            # terminal divergence (never silently returned/persisted).
            if not all_finite((uf, itf)):
                raise TrainDiverged("als", int(config.iterations),
                                    "non-finite factors", 0)
        finally:
            watchdog.stop()
    # Blocked mode pads factor rows to the mesh axis size; the model keeps
    # the true extents.
    if uf.shape[0] != inputs.n_users:
        uf = uf[:inputs.n_users]
    if itf.shape[0] != inputs.n_items:
        itf = itf[:inputs.n_items]
    return ALSModel(user_factors=uf, item_factors=itf, rank=k,
                    implicit=config.implicit)


def _expand_chunks(buckets, specs):
    """Static in-graph slicing of bucket-level arrays into HBM chunks.

    Runs inside :func:`_train_loop` (slices/pads of device arrays are
    free-ish graph ops); mirrors exactly the chunk layout the build
    program used to emit per-chunk (ops/device_prep.py build_buckets'
    chunk tail) before round 4 moved it here to shrink the uncacheable
    prep compile.
    """
    if specs is None:
        return buckets  # pre-chunked (host/mesh path)
    out = []
    for arrs, spec in zip(buckets, specs):
        if spec[0] == "plain":
            idx, vals, msk, rid = arrs
            chunks = spec[1]
            if len(chunks) <= 1:
                out.append(arrs)
                continue
            for cs, cn in chunks:
                out.append((idx[cs:cs + cn], vals[cs:cs + cn],
                            msk[cs:cs + cn], rid[cs:cs + cn]))
        else:
            _, pad_to, chunks = spec
            if not chunks:
                out.append(arrs)
                continue
            idx, vals, msk, seg, ent = arrs
            for e0, e1, r0, r1 in chunks:
                n_chunk = e1 - e0
                seg_pad = (-n_chunk) % pad_to
                row_pad = (-(r1 - r0)) % pad_to
                oob = n_chunk + seg_pad  # padding rows → dropped slot
                seg_c = seg[r0:r1]
                seg_c = jnp.where((seg_c >= e0) & (seg_c < e1),
                                  seg_c - e0, oob)

                def padrows(a):
                    return jnp.pad(a, ((0, row_pad),) + ((0, 0),)
                                   * (a.ndim - 1))

                out.append((padrows(idx[r0:r1]), padrows(vals[r0:r1]),
                            padrows(msk[r0:r1]),
                            jnp.pad(seg_c, (0, row_pad), constant_values=oob),
                            jnp.pad(ent[e0:e1], (0, seg_pad),
                                    constant_values=-1)))
    return tuple(out)


def _resolve_loop_statics(config: ALSConfig, user_buckets, item_buckets,
                          chunk_specs=None):
    """The static arguments of :func:`_train_loop` for this config/layout.

    Shared by the training entry point and the compile pre-warm so both
    hit the same jit-cache entry.  With ``chunk_specs``, kinds/flags are
    emitted per EXPANDED chunk in :func:`_expand_chunks` order.
    """
    k = config.rank
    use_pallas = config.use_pallas
    if use_pallas is None:
        # Default ON for TPU (round 4).  Round-3 measured the einsum path
        # at 250 ms/iter (ML-25M shape): gather+gram 138, solve 32.5,
        # layout copies 47.7, scatter/misc 33.  The copies were XLA
        # relayouting every gathered [R,L,K] block from the gather's
        # K-minor layout to the L-minor layout the gram dots want, and
        # A relayouts feeding the lanes-solve.  The round-4 kernels
        # consume/emit natural layouts end to end (gather → fused gram →
        # in-kernel-transposing solve → scatter), which removes those
        # copies (measured 250.4 → 187.8 ms/iter, copy phase 47.7 → 0.5).
        # (A scalar-loop in-kernel gather measured 0.30 G rows/s — worse
        # than XLA's own engine; don't go back there.)
        use_pallas = pallas_supported()

    def _bucket_pallas(idx) -> bool:
        return use_pallas and fits_vmem(idx.shape[1], k)

    solver = config.solver
    if solver == "auto":
        # The elimination kernels target the VPU; on CPU meshes the XLA
        # Cholesky is fine and interpret-mode Pallas would be slow.
        # High ranks overflow the kernel's VMEM working set — Cholesky.
        solver = "lu" if pallas_supported() and gj_fits_vmem(k) \
            else "cholesky"

    def side_meta(buckets, specs):
        kinds, flags = [], []
        for i, b in enumerate(buckets):
            n = 1
            if specs is not None:
                chunks = specs[i][-1]
                n = max(len(chunks), 1)
            kinds.extend([b[0]] * n)
            flags.extend([_bucket_pallas(b[1])] * n)
        return tuple(kinds), tuple(flags)

    uspec = chunk_specs[0] if chunk_specs else None
    ispec = chunk_specs[1] if chunk_specs else None
    uk, upf = side_meta(user_buckets, uspec)
    ik, ipf = side_meta(item_buckets, ispec)
    return dict(
        kinds=(uk, ik),
        pallas_flags=(upf, ipf),
        implicit=config.implicit,
        gram_dtype=_resolve_gram_dtype(config.gram_dtype),
        solver=solver,
        chunk_specs=chunk_specs,
    )


@functools.partial(jax.jit, static_argnames=(
    "kinds", "pallas_flags", "implicit", "gram_dtype", "solver",
    "factor_shardings", "chunk_specs"))
def _train_loop(uf0, itf0, user_buckets, item_buckets, reg, alpha, iterations,
                *, kinds, pallas_flags, implicit, gram_dtype, solver,
                factor_shardings=(None, None), chunk_specs=None):
    # ``iterations`` is a traced scalar on purpose: the fori_loop bound being
    # dynamic means warmup (1 iter) and the real run (N iters) share one
    # compiled program.
    gdt = jnp.dtype(gram_dtype)
    user_buckets = _expand_chunks(
        user_buckets, chunk_specs[0] if chunk_specs else None)
    item_buckets = _expand_chunks(
        item_buckets, chunk_specs[1] if chunk_specs else None)

    def side(buckets, side_kinds, side_pallas, dst, src, src_sharding):
        # yty hoisted: identical for every bucket of the side (full-matrix
        # gram even in windowed mode — GSPMD reduces the sharded rows to
        # one [K,K], which is the cheap direction).
        yty = gram(src) if implicit else jnp.zeros(
            (src.shape[1], src.shape[1]), jnp.float32)
        for kind, use_pallas, arrs in zip(side_kinds, side_pallas, buckets):
            if kind.endswith("_w"):
                # windowed chunk: fetch only the factor rows it touches
                *arrs, win = arrs
                bsrc = _window_gather(src, win, src_sharding)
            else:
                bsrc = src
            if kind.startswith("merged"):
                idx, vals, msk, seg, ent = arrs
                dst = _merged_solve(idx, vals, msk, seg, ent, dst, bsrc, yty,
                                    reg, alpha, implicit, use_pallas, gdt,
                                    solver)
            else:
                idx, vals, msk, rid = arrs
                solved = _solve_bucket(idx, vals, msk, bsrc, yty, reg, alpha,
                                       implicit, use_pallas, gdt, solver)
                dst = _scatter_rows(dst, rid, solved)
        return dst

    def constrain(x, s):
        return jax.lax.with_sharding_constraint(x, s) if s is not None else x

    def body(_, carry):
        uf, itf = carry
        uf = constrain(side(user_buckets, kinds[0], pallas_flags[0], uf, itf,
                            factor_shardings[1]),
                       factor_shardings[0])
        itf = constrain(side(item_buckets, kinds[1], pallas_flags[1], itf, uf,
                             factor_shardings[0]),
                        factor_shardings[1])
        return (uf, itf)

    return jax.lax.fori_loop(0, iterations, body, (uf0, itf0))


@jax.jit
def predict_scores(user_factors: jax.Array, item_factors: jax.Array,
                   users: jax.Array, items: jax.Array) -> jax.Array:
    """Pointwise r̂_ui for parallel (user, item) id vectors."""
    return jnp.einsum("bk,bk->b", user_factors[users], item_factors[items],
                      preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("k",))
def _recommend_impl(user_factors, item_factors, user_indices, seen, *, k):
    q = user_factors[user_indices]
    return top_k_scores(q, item_factors, k, exclude=seen)


def recommend(
    model: ALSModel,
    user_indices: jax.Array,          # [B] int
    k: int,
    *,
    seen: Optional[jax.Array] = None,  # [B, n_items] bool — exclude
    chunk: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items per user (reference: MLlib recommendProducts).

    Gather + score + top-k is ONE jitted dispatch — the serving path's
    latency budget is dominated by per-call round-trips, not FLOPs.
    """
    if chunk:
        q = model.user_factors[user_indices]
        return chunked_top_k(q, model.item_factors, k, chunk=chunk)
    return _recommend_impl(model.user_factors, model.item_factors,
                           user_indices, seen, k=k)


def fold_in(
    item_factors: np.ndarray,     # [I, K] host item factors (frozen)
    item_ids: np.ndarray,         # [d] int — the user's observed items
    ratings: np.ndarray,          # [d] float — ratings / implicit strengths
    *,
    reg: float,
    alpha: float = 1.0,
    implicit: bool = False,
    yty: Optional[np.ndarray] = None,   # [K, K] — required when implicit
) -> np.ndarray:
    """Serve-time ALS fold-in: one ridge solve for an UNSEEN user against
    the frozen item factors (ISSUE 10).

    This is exactly the user-side normal equation the training sweep
    solves (:func:`_gram_pieces` semantics, ALS-WR ``λ·n_u`` ridge;
    implicit = Hu-Koren-Volinsky with the shared ``YᵀY`` term passed in
    by the caller, cached per generation), in host numpy — rank is tens
    and degree is a visitor's recent-event count, so one K×K solve is
    microseconds and the serving path never pays a device dispatch.
    The folded factor is per-process and ephemeral by design: the next
    refresh trains the user in and makes it durable.
    """
    item_ids = np.asarray(item_ids, np.int64)
    r = np.asarray(ratings, np.float64)
    y = np.asarray(item_factors, np.float64)[item_ids]      # [d, K]
    k = y.shape[1]
    if implicit:
        if yty is None:
            raise ValueError("implicit fold_in needs the cached YᵀY")
        w = alpha * np.abs(r)                               # c - 1
        c = (1.0 + w) * (r > 0)
        a = np.asarray(yty, np.float64) + (y * w[:, None]).T @ y
        b = y.T @ c
    else:
        a = y.T @ y
        b = y.T @ r
    n = max(len(item_ids), 1)
    a = a + reg * n * np.eye(k)
    try:
        u = np.linalg.solve(a, b)
    except np.linalg.LinAlgError:                       # singular corner:
        u = np.linalg.lstsq(a, b, rcond=None)[0]        # degenerate events
    return u.astype(np.float32)


def rmse(model: ALSModel, user_ids, item_ids, ratings) -> float:
    """Explicit-feedback fit metric (host-side convenience)."""
    pred = predict_scores(model.user_factors, model.item_factors,
                          jnp.asarray(user_ids), jnp.asarray(item_ids))
    return float(jnp.sqrt(jnp.mean((pred - jnp.asarray(ratings)) ** 2)))
