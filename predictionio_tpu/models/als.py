"""Alternating least squares, TPU-shaped.

Reference behavior: Spark MLlib ``ALS.train`` / ``ALS.trainImplicit`` as
invoked by the recommendation template (SURVEY.md §2.2, §3.1 hot loop).
MLlib's implementation is shuffle-shaped: user×item factor blocks exchanged
between executors, per-block normal equations solved via JNI BLAS.

The TPU design replaces all of that with one batched XLA program per side
per iteration (SURVEY.md §7 step 5):

- ragged ratings → degree-bucketed padded blocks (host-side, once)
- per-entity normal equations built by batched einsum over gathered
  factors (MXU) — ``A_u = Σ_i w_ui · y_i y_iᵀ``
- batched Cholesky solves (``ops.linalg.batched_ridge_solve``)
- factor "exchange" = nothing within a chip, an all-gather across the mesh
  (factors replicated; solve rows sharded on the ``data`` axis)

Regularization follows MLlib's ALS-WR scaling: λ·n_u per user (n_u = that
user's rating count), λ·n_i per item.  Implicit feedback follows
Hu-Koren-Volinsky: confidence c = 1 + α·r, preference p = 1(r>0), with the
``YᵀY`` term shared across users.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.linalg import gram, masked_gram
from predictionio_tpu.ops.pallas_kernels import (
    fits_vmem,
    fused_gram_vector_pallas,
    pallas_supported,
)
from predictionio_tpu.ops.ragged import Padded, bucket_by_length
from predictionio_tpu.ops.topk import chunked_top_k, top_k_scores
from predictionio_tpu.parallel.mesh import AXIS_DATA

__all__ = ["ALSConfig", "ALSModel", "train_als", "recommend", "predict_scores"]


@dataclasses.dataclass
class ALSConfig:
    rank: int = 32
    iterations: int = 10
    reg: float = 0.01          # MLlib regParam (λ), ALS-WR scaled by degree
    alpha: float = 1.0         # implicit confidence scale
    implicit: bool = False
    max_degree: Optional[int] = None   # truncate overlong entities (None = exact)
    bucket_bounds: Sequence[int] = (16, 64, 256, 1024, 4096, 16384)
    seed: int = 42
    dtype: str = "float32"     # factor storage dtype; solves always f32
    use_pallas: Optional[bool] = None  # None = auto (on for single-chip TPU)
    # HBM guard: cap the gathered [rows, L, K] block at this many floats;
    # jumbo buckets are solved in row chunks (≈1 GB at the default).
    max_block_floats: int = 1 << 28


@dataclasses.dataclass
class ALSModel:
    """Trained factors. ``user_factors [U,K]``, ``item_factors [I,K]``."""

    user_factors: jax.Array
    item_factors: jax.Array
    rank: int
    implicit: bool

    def tree_flatten(self):  # manual pytree-ish helpers for checkpointing
        return {"user_factors": self.user_factors, "item_factors": self.item_factors}


def _solve_bucket(
    indices: jax.Array,    # [R, L] int32 — other-side ids
    values: jax.Array,     # [R, L] f32
    mask: jax.Array,       # [R, L] bool
    factors: jax.Array,    # [N, K] other-side factors
    yty: jax.Array,        # [K, K] — YᵀY (zeros when explicit)
    reg: jax.Array,        # scalar λ
    alpha: jax.Array,      # scalar α
    implicit: bool,
    use_pallas: bool,
) -> jax.Array:
    """One padded block of normal equations + Cholesky solves → [R, K]."""
    f = factors[indices]                      # [R, L, K] gather
    m = mask.astype(jnp.float32)
    if implicit:
        # Hu-Koren-Volinsky per MLlib: c = 1 + α·|r|, p = 1(r>0).
        # A = YᵀY + Σ (c-1)·y yᵀ,  b = Σ c·p·y — (c-1) ≥ 0 keeps A PSD.
        w = alpha * jnp.abs(values) * m       # c - 1
        cvec = (1.0 + w) * (values > 0).astype(jnp.float32) * m
    else:
        w = m
        cvec = values * m
    if use_pallas:
        a, b = fused_gram_vector_pallas(f, w, cvec)
    else:
        a = masked_gram(f, w)
        b = jnp.einsum("blk,bl->bk", f, cvec,
                       preferred_element_type=jnp.float32)
    if implicit:
        a = yty[None, :, :] + a
    degree = jnp.maximum(m.sum(axis=1), 1.0)  # ALS-WR: λ·n_u
    return _ridge(a, b, reg * degree)


def _ridge(a: jax.Array, b: jax.Array, reg_vec: jax.Array) -> jax.Array:
    k = a.shape[-1]
    eye = jnp.eye(k, dtype=a.dtype)
    a_reg = a + reg_vec[:, None, None] * eye
    chol = jnp.linalg.cholesky(a_reg)
    y = jax.scipy.linalg.solve_triangular(chol, b[..., None], lower=True)
    x = jax.scipy.linalg.solve_triangular(chol, y, lower=True, trans="T")
    return x[..., 0]


def _scatter_rows(dst: jax.Array, row_ids: jax.Array, rows: jax.Array) -> jax.Array:
    """Write solved rows back; row_id == -1 rows (bucket padding) dropped.

    Invalid rows are routed out-of-bounds so ``mode="drop"`` discards them —
    never clamp them to a real index (a clamped duplicate write races the
    genuine row-0 update).
    """
    safe = jnp.where(row_ids >= 0, row_ids, dst.shape[0])
    return dst.at[safe].set(rows, mode="drop")


@functools.partial(jax.jit, static_argnames=("implicit", "use_pallas"))
def _side_step(
    indices, values, mask, row_ids, dst_factors, src_factors, reg, alpha, *,
    implicit, use_pallas,
):
    yty = gram(src_factors) if implicit else jnp.zeros(
        (src_factors.shape[1], src_factors.shape[1]), jnp.float32)
    solved = _solve_bucket(indices, values, mask, src_factors, yty, reg, alpha,
                           implicit, use_pallas)
    return _scatter_rows(dst_factors, row_ids, solved)


def _device_buckets(
    buckets: List[Padded],
    mesh: Optional[Mesh],
    rank: int,
    max_block_floats: int,
    pad_rows: int,
) -> List[Tuple]:
    """Transfer padded buckets, splitting any whose gathered [R, L, K]
    block would exceed the HBM budget into fixed-shape row chunks (last
    chunk row-padded with row_id = -1, which the scatter drops)."""
    out = []
    for p in buckets:
        r, l = p.indices.shape
        rows_max = max(pad_rows, (max_block_floats // max(l * rank, 1))
                       // pad_rows * pad_rows)
        chunks = [(p.indices, p.values, p.mask, p.row_ids)] if r <= rows_max \
            else []
        if r > rows_max:
            for start in range(0, r, rows_max):
                sl = slice(start, start + rows_max)
                idx, vals = p.indices[sl], p.values[sl]
                msk, rid = p.mask[sl], p.row_ids[sl]
                short = rows_max - idx.shape[0]
                if short:
                    idx = np.pad(idx, ((0, short), (0, 0)))
                    vals = np.pad(vals, ((0, short), (0, 0)))
                    msk = np.pad(msk, ((0, short), (0, 0)))
                    rid = np.pad(rid, (0, short), constant_values=-1)
                chunks.append((idx, vals, msk, rid))
        for idx, vals, msk, rid in chunks:
            arrs = (jnp.asarray(idx), jnp.asarray(vals),
                    jnp.asarray(msk), jnp.asarray(rid))
            if mesh is not None:
                row = NamedSharding(mesh, P(AXIS_DATA))
                arrs = tuple(jax.device_put(a, row) for a in arrs)
            out.append(arrs)
    return out


def train_als(
    user_ids: np.ndarray,
    item_ids: np.ndarray,
    ratings: Optional[np.ndarray],
    n_users: int,
    n_items: int,
    config: ALSConfig,
    mesh: Optional[Mesh] = None,
) -> ALSModel:
    """Train from COO triplets.

    With a mesh, solve rows are sharded over the ``data`` axis and factors
    are replicated — the per-iteration factor exchange is the implicit
    all-gather XLA inserts, riding ICI (reference: Spark shuffle between
    in/out ALS blocks).
    """
    rng = np.random.default_rng(config.seed)
    k = config.rank
    pad_rows = mesh.shape[AXIS_DATA] if mesh is not None else 1
    # Deterministic scaled-normal init (MLlib uses Xavier-ish normal / sqrt(k)).
    uf = jnp.asarray(rng.standard_normal((n_users, k), dtype=np.float32) / np.sqrt(k))
    itf = jnp.asarray(rng.standard_normal((n_items, k), dtype=np.float32) / np.sqrt(k))
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        uf = jax.device_put(uf, rep)
        itf = jax.device_put(itf, rep)

    user_buckets = _device_buckets(
        bucket_by_length(user_ids, item_ids, ratings, n_users,
                         bucket_bounds=config.bucket_bounds,
                         max_len=config.max_degree, pad_rows_to=pad_rows),
        mesh, k, config.max_block_floats, pad_rows,
    )
    item_buckets = _device_buckets(
        bucket_by_length(item_ids, user_ids, ratings, n_items,
                         bucket_bounds=config.bucket_bounds,
                         max_len=config.max_degree, pad_rows_to=pad_rows),
        mesh, k, config.max_block_floats, pad_rows,
    )
    reg = jnp.float32(config.reg)
    alpha = jnp.float32(config.alpha)
    use_pallas = config.use_pallas
    if use_pallas is None:
        # Default OFF: measured on v5e, XLA fuses the factor gather into
        # the einsum consumer (no [R,L,K] materialization), which beats the
        # fused kernel fed from materialized inputs.  The kernel stays
        # available for explicit opt-in; a gather-inside-kernel variant
        # (scalar-prefetch indices + per-row DMA) is the follow-up that
        # could win outright.
        use_pallas = False
    def _bucket_pallas(idx) -> bool:
        # Jumbo buckets (max-degree outliers) exceed the per-program VMEM
        # tile budget — those take the einsum path.
        return use_pallas and fits_vmem(idx.shape[1], k)

    for _ in range(config.iterations):
        for idx, vals, msk, rid in user_buckets:
            uf = _side_step(idx, vals, msk, rid, uf, itf, reg, alpha,
                            implicit=config.implicit,
                            use_pallas=_bucket_pallas(idx))
        for idx, vals, msk, rid in item_buckets:
            itf = _side_step(idx, vals, msk, rid, itf, uf, reg, alpha,
                             implicit=config.implicit,
                             use_pallas=_bucket_pallas(idx))
    return ALSModel(user_factors=uf, item_factors=itf, rank=k,
                    implicit=config.implicit)


@jax.jit
def predict_scores(user_factors: jax.Array, item_factors: jax.Array,
                   users: jax.Array, items: jax.Array) -> jax.Array:
    """Pointwise r̂_ui for parallel (user, item) id vectors."""
    return jnp.einsum("bk,bk->b", user_factors[users], item_factors[items],
                      preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("k",))
def _recommend_impl(user_factors, item_factors, user_indices, seen, *, k):
    q = user_factors[user_indices]
    return top_k_scores(q, item_factors, k, exclude=seen)


def recommend(
    model: ALSModel,
    user_indices: jax.Array,          # [B] int
    k: int,
    *,
    seen: Optional[jax.Array] = None,  # [B, n_items] bool — exclude
    chunk: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k items per user (reference: MLlib recommendProducts).

    Gather + score + top-k is ONE jitted dispatch — the serving path's
    latency budget is dominated by per-call round-trips, not FLOPs.
    """
    if chunk:
        q = model.user_factors[user_indices]
        return chunked_top_k(q, model.item_factors, k, chunk=chunk)
    return _recommend_impl(model.user_factors, model.item_factors,
                           user_indices, seen, k=k)


def rmse(model: ALSModel, user_ids, item_ids, ratings) -> float:
    """Explicit-feedback fit metric (host-side convenience)."""
    pred = predict_scores(model.user_factors, model.item_factors,
                          jnp.asarray(user_ids), jnp.asarray(item_ids))
    return float(jnp.sqrt(jnp.mean((pred - jnp.asarray(ratings)) ** 2)))
