"""Model library — TPU-native algorithms backing the engine templates.

Each module is a pure numeric component (numpy/COO in, jax pytrees out);
the DASE templates in :mod:`predictionio_tpu.templates` wrap these with
event reading, id indexing, and serving logic.

- :mod:`als`       — blocked explicit/implicit ALS (reference: Spark MLlib
  ``ALS.train``/``trainImplicit`` behind the recommendation template)
- :mod:`linear`    — logistic regression / softmax classifier (reference:
  MLlib LogisticRegression/NaiveBayes behind the classification template)
- :mod:`naive_bayes` — multinomial naive Bayes (one-pass psum counts)
- :mod:`two_tower` — neural retrieval, DP over the mesh (BASELINE config 4)
- :mod:`dlrm`      — CTR ranking with row-sharded embeddings (config 5)
"""
