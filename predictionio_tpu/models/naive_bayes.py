"""Gaussian / multinomial naive Bayes — one-pass sufficient statistics.

Reference: Spark MLlib ``NaiveBayes.train`` as used by the classification
template (SURVEY.md §2.2) and e2's CategoricalNaiveBayes (§2.1).  MLlib
computes per-class counts with ``treeAggregate``; on TPU the same
sufficient statistics are segment-sums on device, and the hierarchical
reduction is a ``psum`` when the batch is sharded (SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import AXIS_DATA, put_sharded

__all__ = ["NaiveBayesModel", "train_multinomial", "train_gaussian",
           "predict_log_proba"]


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["class_log_prior", "feature_log_prob", "means", "variances"],
    meta_fields=["kind"])
@dataclasses.dataclass
class NaiveBayesModel:
    kind: str                 # "multinomial" | "gaussian"
    class_log_prior: jax.Array      # [C]
    # multinomial: feature log-likelihoods [C, D]
    # gaussian: means [C, D] and variances [C, D]
    feature_log_prob: Optional[jax.Array] = None
    means: Optional[jax.Array] = None
    variances: Optional[jax.Array] = None


def _one_hot_counts(labels: jax.Array, n_classes: int) -> jax.Array:
    return jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)


@jax.jit
def _multinomial_stats(x: jax.Array, y_onehot: jax.Array):
    class_count = jnp.sum(y_onehot, axis=0)                      # [C]
    feat_count = jnp.einsum("bc,bd->cd", y_onehot, x,
                            preferred_element_type=jnp.float32)  # [C, D]
    return class_count, feat_count


def train_multinomial(
    x: np.ndarray, y: np.ndarray, n_classes: int, *,
    alpha: float = 1.0, mesh: Optional[Mesh] = None,
) -> NaiveBayesModel:
    """MLlib-parity multinomial NB with Laplace smoothing ``alpha``."""
    xj = jnp.asarray(x, jnp.float32)
    yj = _one_hot_counts(jnp.asarray(y), n_classes)
    if mesh is not None:
        sh = NamedSharding(mesh, P(AXIS_DATA))
        xj = put_sharded(xj, mesh, sh)
        yj = put_sharded(yj, mesh, sh)
    class_count, feat_count = _multinomial_stats(xj, yj)
    log_prior = jnp.log(class_count) - jnp.log(jnp.sum(class_count))
    smoothed = feat_count + alpha
    log_prob = jnp.log(smoothed) - jnp.log(
        jnp.sum(smoothed, axis=1, keepdims=True))
    return NaiveBayesModel(kind="multinomial", class_log_prior=log_prior,
                           feature_log_prob=log_prob)


@jax.jit
def _gaussian_stats(x: jax.Array, y_onehot: jax.Array):
    class_count = jnp.sum(y_onehot, axis=0)
    s1 = jnp.einsum("bc,bd->cd", y_onehot, x,
                    preferred_element_type=jnp.float32)
    s2 = jnp.einsum("bc,bd->cd", y_onehot, x * x,
                    preferred_element_type=jnp.float32)
    return class_count, s1, s2


def train_gaussian(
    x: np.ndarray, y: np.ndarray, n_classes: int, *,
    var_smoothing: float = 1e-6, mesh: Optional[Mesh] = None,
) -> NaiveBayesModel:
    xj = jnp.asarray(x, jnp.float32)
    yj = _one_hot_counts(jnp.asarray(y), n_classes)
    if mesh is not None:
        sh = NamedSharding(mesh, P(AXIS_DATA))
        xj = put_sharded(xj, mesh, sh)
        yj = put_sharded(yj, mesh, sh)
    n, s1, s2 = _gaussian_stats(xj, yj)
    n_safe = jnp.maximum(n, 1.0)[:, None]
    means = s1 / n_safe
    variances = jnp.maximum(s2 / n_safe - means ** 2, 0.0) + var_smoothing
    log_prior = jnp.log(jnp.maximum(n, 1e-12)) - jnp.log(jnp.sum(n))
    return NaiveBayesModel(kind="gaussian", class_log_prior=log_prior,
                           means=means, variances=variances)


def predict_log_proba(model: NaiveBayesModel, x: jax.Array) -> jax.Array:
    """[B, C] unnormalized class log-posteriors."""
    x = jnp.asarray(x, jnp.float32)
    if model.kind == "multinomial":
        return model.class_log_prior[None, :] + jnp.einsum(
            "bd,cd->bc", x, model.feature_log_prob,
            preferred_element_type=jnp.float32)
    ll = -0.5 * (
        jnp.log(2 * jnp.pi * model.variances)[None, :, :]
        + (x[:, None, :] - model.means[None, :, :]) ** 2
        / model.variances[None, :, :]
    ).sum(axis=-1)
    return model.class_log_prior[None, :] + ll
