"""predictionio_tpu — a TPU-native machine-learning server.

A ground-up rebuild of the capabilities of Apache PredictionIO
(reference fork: Algorithmicinsights/predictionio) with the Spark/MLlib
compute substrate replaced by JAX/XLA/Pallas on a TPU ICI mesh:

- **Event Server** — HTTP ingestion of behavioral JSON events into an
  append-only, channel-partitioned event store
  (reference: data/src/main/scala/org/apache/predictionio/data/api/).
- **DASE controller API** — DataSource / Preparator / Algorithm / Serving /
  Evaluator engine contract
  (reference: core/src/main/scala/org/apache/predictionio/controller/).
- **Workflow** — train / eval orchestration with engine-instance lifecycle
  (reference: core/src/main/scala/org/apache/predictionio/workflow/).
- **Serving** — low-latency REST `/queries.json` with continuous batching on
  compiled XLA executables
  (reference: core/.../workflow/CreateServer.scala).
- **CLI** — `pio`-style verbs (app / accesskey / train / deploy / eval /
  eventserver / import / export / status)
  (reference: tools/src/main/scala/org/apache/predictionio/tools/).

The compute path is idiomatic JAX: engines' train/predict compile with
`jax.jit` over a `jax.sharding.Mesh` (data / model / sequence / expert axes),
inter-chip traffic is XLA collectives over ICI, and hot ops get Pallas
kernels where XLA's defaults underperform.
"""

from predictionio_tpu.version import __version__

__all__ = ["__version__"]
