"""Client SDK — EventClient / EngineClient over HTTP.

Reference parity: the PredictionIO ecosystem ships a ``predictionio``
Python SDK with ``EventClient`` (create_event/get_event/delete_event,
``pio import``-style batch) and ``EngineClient`` (send_query).  Same
surface here, stdlib-only, so reference users can port scripts by
changing an import.
"""

from __future__ import annotations

import datetime as _dt
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Mapping, Optional, Sequence

__all__ = ["PredictionIOError", "EventClient", "EngineClient"]


class PredictionIOError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


def _request(method: str, url: str, body: Optional[Any] = None,
             timeout: float = 10.0) -> Any:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else None
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            msg = json.loads(payload).get("message", "") if payload else ""
        except json.JSONDecodeError:
            msg = payload.decode(errors="replace")[:200]
        raise PredictionIOError(e.code, msg) from None


class EventClient:
    """Talks to the Event Server (reference: predictionio.EventClient)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0):
        self.access_key = access_key
        self.base = url.rstrip("/")
        self.channel = channel
        self.timeout = timeout

    def _qs(self, extra: Optional[Mapping[str, Any]] = None) -> str:
        params: Dict[str, Any] = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        if extra:
            params.update({k: v for k, v in extra.items() if v is not None})
        return urllib.parse.urlencode(params, doseq=True)

    @staticmethod
    def _iso(t) -> Optional[str]:
        if t is None:
            return None
        if isinstance(t, _dt.datetime):
            return t.isoformat()
        return str(t)

    def create_event(self, event: str, entity_type: str, entity_id: str,
                     target_entity_type: Optional[str] = None,
                     target_entity_id: Optional[str] = None,
                     properties: Optional[Mapping[str, Any]] = None,
                     event_time=None) -> str:
        body: Dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": entity_id}
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = target_entity_id
        if properties:
            body["properties"] = dict(properties)
        if event_time is not None:
            body["eventTime"] = self._iso(event_time)
        out = _request("POST", f"{self.base}/events.json?{self._qs()}", body,
                       self.timeout)
        return out["eventId"]

    def create_events(self, events: Sequence[Mapping[str, Any]]) -> List[Dict]:
        """Batch ingest (reference: /batch/events.json, ≤50 per call)."""
        return _request("POST", f"{self.base}/batch/events.json?{self._qs()}",
                        list(events), self.timeout)

    def get_event(self, event_id: str) -> Dict[str, Any]:
        return _request("GET",
                        f"{self.base}/events/{event_id}.json?{self._qs()}",
                        timeout=self.timeout)

    def delete_event(self, event_id: str) -> None:
        _request("DELETE", f"{self.base}/events/{event_id}.json?{self._qs()}",
                 timeout=self.timeout)

    def find_events(self, **filters) -> List[Dict[str, Any]]:
        """Filters: startTime, untilTime, entityType, entityId, event,
        targetEntityType, targetEntityId, limit, reversed."""
        qs = self._qs({k: (str(v).lower() if isinstance(v, bool) else v)
                       for k, v in filters.items()})
        try:
            return _request("GET", f"{self.base}/events.json?{qs}",
                            timeout=self.timeout)
        except PredictionIOError as e:
            if e.status == 404:
                return []
            raise

    # Convenience wrappers (reference SDK surface).
    def set_user(self, uid: str, properties=None, event_time=None) -> str:
        return self.create_event("$set", "user", uid, properties=properties,
                                 event_time=event_time)

    def set_item(self, iid: str, properties=None, event_time=None) -> str:
        return self.create_event("$set", "item", iid, properties=properties,
                                 event_time=event_time)

    def record_user_action_on_item(self, action: str, uid: str, iid: str,
                                   properties=None, event_time=None) -> str:
        return self.create_event(action, "user", uid, "item", iid,
                                 properties, event_time)


class EngineClient:
    """Talks to a deployed engine (reference: predictionio.EngineClient)."""

    def __init__(self, url: str = "http://localhost:8000",
                 timeout: float = 10.0):
        self.base = url.rstrip("/")
        self.timeout = timeout

    def send_query(self, query: Mapping[str, Any]) -> Dict[str, Any]:
        return _request("POST", f"{self.base}/queries.json", dict(query),
                        self.timeout)

    def status(self) -> Dict[str, Any]:
        return _request("GET", f"{self.base}/", timeout=self.timeout)
