"""Client SDK — EventClient / EngineClient over HTTP.

Reference parity: the PredictionIO ecosystem ships a ``predictionio``
Python SDK with ``EventClient`` (create_event/get_event/delete_event,
``pio import``-style batch) and ``EngineClient`` (send_query).  Same
surface here, stdlib-only, so reference users can port scripts by
changing an import.

Resilience additions (README "Resilience"):

- ONE exception surface: every transport failure — HTTP error status,
  refused connection, DNS failure, timeout — raises
  :class:`PredictionIOError`.  Connection-level failures carry
  ``status=None`` and ``retriable=True``; 429/502/503/504 are marked
  retriable and surface the server's ``Retry-After`` hint as
  ``retry_after_s``.
- Opt-in retries: construct a client with ``retries=N`` and retriable
  failures are retried with jittered exponential backoff
  (``Retry-After``-aware).  Caveat: the HTTP event API carries no
  idempotency token, so a retried POST whose first attempt committed
  before the reply was lost inserts a duplicate — HTTP ingest retries
  are AT-LEAST-ONCE (that is why they are opt-in).  Exactly-once
  machinery lives a layer down, on the storage JSON-RPC protocol and
  the server's spill-replay path.
- Deadline propagation: ``deadline_ms=...`` stamps every request with
  ``X-PIO-Deadline-Ms`` so servers can shed work that cannot finish in
  budget (504) instead of queueing it.
- Typed ingest result: ``create_event`` returns :class:`EventResult` — a
  ``str`` (the old return shape) that also says whether the value is a
  durably-stored event id (201) or a 202 spill token.
"""

from __future__ import annotations

import datetime as _dt
import http.client
import json
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any, Dict, List, Mapping, Optional, Sequence

from predictionio_tpu.resilience.deadline import DEADLINE_HEADER
from predictionio_tpu.resilience.policy import RetryPolicy

__all__ = ["PredictionIOError", "EventResult", "EventClient", "EngineClient"]


class EventResult(str):
    """``create_event``'s typed result (ROADMAP resilience follow-on (e)).

    A ``str`` subclass, so every existing caller that treated the return
    value as "the id string" keeps working unchanged — but the value a
    202 carries is a spill TOKEN, not an event id (the real id is
    assigned at replay and cannot be fetched/deleted by token).  New
    callers distinguish the two::

        r = client.create_event(...)
        if r.stored:          # 201: durably stored, r.event_id is real
            audit(r.event_id)
        else:                 # 202: journaled server-side, r.token
            metrics.spilled += 1

    ``status`` carries the HTTP status (201 or 202).
    """

    __slots__ = ("event_id", "token", "status")

    def __new__(cls, value: str, *, event_id: Optional[str] = None,
                token: Optional[str] = None, status: Optional[int] = None):
        self = super().__new__(cls, value)
        self.event_id = event_id
        self.token = token
        self.status = status
        return self

    @property
    def stored(self) -> bool:
        """True when the event is durably stored under ``event_id``;
        False when it was 202-journaled for replay (``token``)."""
        return self.event_id is not None


class PredictionIOError(RuntimeError):
    """The SDK's one exception surface.

    ``status`` is the HTTP status, or None for connection-level failures
    (refused, reset, DNS, timeout).  ``retriable`` marks failures a
    retry could plausibly fix; ``retry_after_s`` carries the server's
    ``Retry-After`` backoff hint when present.
    """

    def __init__(self, status: Optional[int], message: str,
                 retriable: bool = False,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}" if status is not None
                         else f"connection error: {message}")
        self.status = status
        self.retriable = retriable
        self.retry_after_s = retry_after_s


# Transient server statuses a retry could fix (tail-at-scale playbook).
_RETRIABLE_STATUSES = frozenset({429, 502, 503, 504})


def _retry_after_s(headers) -> Optional[float]:
    try:
        raw = headers.get("Retry-After") if headers else None
        return float(raw) if raw else None
    except (TypeError, ValueError):
        return None


def _request(method: str, url: str, body: Optional[Any] = None,
             timeout: float = 10.0, *, retry: Optional[RetryPolicy] = None,
             deadline_ms: Optional[float] = None,
             want_status: bool = False) -> Any:
    """``want_status=True`` returns ``(http_status, payload)`` — the
    typed create_event result needs to tell a 201 from a 202."""
    data = json.dumps(body).encode() if body is not None else None
    # One absolute deadline for the WHOLE call, retries included: each
    # attempt sends the REMAINING budget (the header's documented
    # meaning) and stops — non-retriably — once it is spent, so retry
    # backoff can never stretch a 200ms-budget call to seconds.
    t_end = (time.monotonic() + deadline_ms / 1e3
             if deadline_ms is not None else None)

    def attempt() -> Any:
        headers = {"Content-Type": "application/json"}
        attempt_timeout = timeout
        if t_end is not None:
            remaining = (t_end - time.monotonic()) * 1e3
            if remaining <= 0:
                raise PredictionIOError(
                    None, f"deadline exhausted before {method} {url}",
                    retriable=False)
            headers[DEADLINE_HEADER] = str(int(remaining))
            attempt_timeout = min(timeout, remaining / 1e3)
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=attempt_timeout) as resp:
                payload = resp.read()
                parsed = json.loads(payload) if payload else None
                return (resp.status, parsed) if want_status else parsed
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload).get("message", "") if payload else ""
            except json.JSONDecodeError:
                msg = payload.decode(errors="replace")[:200]
            raise PredictionIOError(
                e.code, msg, retriable=e.code in _RETRIABLE_STATUSES,
                retry_after_s=_retry_after_s(e.headers)) from None
        except (urllib.error.URLError, OSError, TimeoutError,
                http.client.HTTPException) as e:
            # URLError wraps socket errors; ConnectionError/timeout can
            # escape raw; a server dying mid-response raises
            # http.client exceptions (IncompleteRead, BadStatusLine).
            # Normalize ALL of them: one exception surface, status None,
            # retriable.
            reason = getattr(e, "reason", None) or e
            raise PredictionIOError(None, str(reason),
                                    retriable=True) from None

    if retry is not None:
        return retry.run(attempt,
                         retriable=lambda e: isinstance(e, PredictionIOError)
                         and e.retriable,
                         deadline_ts=t_end)
    return attempt()


def _policy(retries: int) -> Optional[RetryPolicy]:
    return RetryPolicy(max_attempts=retries + 1) if retries > 0 else None


class EventClient:
    """Talks to the Event Server (reference: predictionio.EventClient)."""

    def __init__(self, access_key: str, url: str = "http://localhost:7070",
                 channel: Optional[str] = None, timeout: float = 10.0,
                 retries: int = 0, deadline_ms: Optional[float] = None):
        self.access_key = access_key
        self.base = url.rstrip("/")
        self.channel = channel
        self.timeout = timeout
        self.deadline_ms = deadline_ms
        self.retry = _policy(retries)

    def _qs(self, extra: Optional[Mapping[str, Any]] = None) -> str:
        params: Dict[str, Any] = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        if extra:
            params.update({k: v for k, v in extra.items() if v is not None})
        return urllib.parse.urlencode(params, doseq=True)

    def _request(self, method: str, url: str, body: Optional[Any] = None,
                 **kw) -> Any:
        return _request(method, url, body, self.timeout, retry=self.retry,
                        deadline_ms=self.deadline_ms, **kw)

    @staticmethod
    def _iso(t) -> Optional[str]:
        if t is None:
            return None
        if isinstance(t, _dt.datetime):
            return t.isoformat()
        return str(t)

    def create_event(self, event: str, entity_type: str, entity_id: str,
                     target_entity_type: Optional[str] = None,
                     target_entity_id: Optional[str] = None,
                     properties: Optional[Mapping[str, Any]] = None,
                     event_time=None) -> EventResult:
        body: Dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": entity_id}
        if target_entity_type:
            body["targetEntityType"] = target_entity_type
        if target_entity_id:
            body["targetEntityId"] = target_entity_id
        if properties:
            body["properties"] = dict(properties)
        if event_time is not None:
            body["eventTime"] = self._iso(event_time)
        status, out = self._request(
            "POST", f"{self.base}/events.json?{self._qs()}", body,
            want_status=True)
        # 201 carries eventId; a 202 (storage outage, event journaled
        # server-side) carries the spill token instead.  The returned
        # EventResult IS the old string (compat) plus .event_id/.token/
        # .stored so callers can finally tell them apart.
        out = out or {}
        event_id = out.get("eventId")
        token = out.get("token")
        return EventResult(event_id or token or "", event_id=event_id,
                           token=token, status=status)

    def create_events(self, events: Sequence[Mapping[str, Any]],
                      batch_token: Optional[str] = None) -> List[EventResult]:
        """Bulk ingest riding ``POST /batch/events.json`` (ISSUE 17).

        One idempotency ``batch_token`` covers the whole batch
        (auto-generated when not given): the server derives per-item
        sub-tokens — and thus event ids — from it, so an opt-in retry
        (``retries=N``) that re-sends the batch after a lost reply lands
        every row AT MOST once.  Unlike single-event ``create_event``,
        batch retries are exactly-once end-to-end.

        Returns one typed :class:`EventResult` per item, in order:
        ``.stored`` (201) with ``.event_id``, a 202 spill ``.token``, or
        a per-item error (``.status`` 400/403 — one malformed item never
        fails its cohort).  Old servers without the bulk endpoint (404)
        degrade to a per-row ``create_event`` loop — at-least-once, like
        any single-event retry.
        """
        items = [dict(e) for e in events]
        token = batch_token or uuid.uuid4().hex
        try:
            out = self._request(
                "POST",
                f"{self.base}/batch/events.json?"
                f"{self._qs({'batchToken': token})}",
                items)
        except PredictionIOError as e:
            if e.status in (404, 405):  # pre-bulk server: row-loop
                return [self._create_event_json(it) for it in items]
            raise
        results: List[EventResult] = []
        for item in out or []:
            eid = item.get("eventId")
            tok = item.get("token")
            results.append(EventResult(
                eid or tok or "", event_id=eid, token=tok,
                status=item.get("status")))
        return results

    def _create_event_json(self, body: Mapping[str, Any]) -> EventResult:
        """Row-loop fallback: POST one already-shaped event JSON.  A
        per-item failure becomes an errored EventResult (status carried
        over) so the fallback keeps the bulk path's one-bad-row-never-
        fails-the-cohort contract."""
        try:
            status, out = self._request(
                "POST", f"{self.base}/events.json?{self._qs()}", dict(body),
                want_status=True)
        except PredictionIOError as e:
            if e.status is None:
                raise  # connection-level: the whole loop is doomed
            return EventResult("", status=e.status)
        out = out or {}
        return EventResult(out.get("eventId") or out.get("token") or "",
                           event_id=out.get("eventId"),
                           token=out.get("token"), status=status)

    def get_event(self, event_id: str) -> Dict[str, Any]:
        return self._request(
            "GET", f"{self.base}/events/{event_id}.json?{self._qs()}")

    def delete_event(self, event_id: str) -> None:
        self._request("DELETE",
                      f"{self.base}/events/{event_id}.json?{self._qs()}")

    def find_events(self, **filters) -> List[Dict[str, Any]]:
        """Filters: startTime, untilTime, entityType, entityId, event,
        targetEntityType, targetEntityId, limit, reversed."""
        qs = self._qs({k: (str(v).lower() if isinstance(v, bool) else v)
                       for k, v in filters.items()})
        try:
            return self._request("GET", f"{self.base}/events.json?{qs}")
        except PredictionIOError as e:
            if e.status == 404:
                return []
            raise

    # Convenience wrappers (reference SDK surface).
    def set_user(self, uid: str, properties=None, event_time=None) -> str:
        return self.create_event("$set", "user", uid, properties=properties,
                                 event_time=event_time)

    def set_item(self, iid: str, properties=None, event_time=None) -> str:
        return self.create_event("$set", "item", iid, properties=properties,
                                 event_time=event_time)

    def record_user_action_on_item(self, action: str, uid: str, iid: str,
                                   properties=None, event_time=None) -> str:
        return self.create_event(action, "user", uid, "item", iid,
                                 properties, event_time)


class EngineClient:
    """Talks to a deployed engine (reference: predictionio.EngineClient)."""

    def __init__(self, url: str = "http://localhost:8000",
                 timeout: float = 10.0, retries: int = 0,
                 deadline_ms: Optional[float] = None):
        self.base = url.rstrip("/")
        self.timeout = timeout
        self.deadline_ms = deadline_ms
        self.retry = _policy(retries)

    def send_query(self, query: Mapping[str, Any]) -> Dict[str, Any]:
        return _request("POST", f"{self.base}/queries.json", dict(query),
                        self.timeout, retry=self.retry,
                        deadline_ms=self.deadline_ms)

    def status(self) -> Dict[str, Any]:
        return _request("GET", f"{self.base}/", timeout=self.timeout,
                        retry=self.retry, deadline_ms=self.deadline_ms)
