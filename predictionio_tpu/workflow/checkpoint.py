"""Training checkpoints + resume — a capability the reference lacks.

Reference gap (SURVEY.md §5.4): PredictionIO persists *final* models only;
a killed ``pio train`` restarts from scratch (Spark checkpointing inside
MLlib ALS only truncates RDD lineage).  Here mid-training resume is
first-class: orbax async sharded checkpoints every N steps, restored
automatically when a training loop starts over the same directory.

Usage::

    ckpt = TrainCheckpointer(dir, save_every=200)
    start = ckpt.restore_step(state_like)     # 0 if fresh
    state = ckpt.restored_state or state
    for step in range(start, total):
        state, loss = train_step(...)
        ckpt.maybe_save(step + 1, state)
    ckpt.finalize()
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

import jax

logger = logging.getLogger(__name__)

__all__ = ["TrainCheckpointer"]


class TrainCheckpointer:
    """Thin orbax CheckpointManager wrapper for pytree train states.

    Saves are async (orbax default) — the device keeps training while the
    host serializes.  Restore uses the latest complete step.  Sharded
    ``jax.Array`` leaves round-trip with their shardings preserved when the
    same mesh is live.
    """

    def __init__(self, directory, *, save_every: int = 0, keep: int = 3):
        self.directory = Path(directory).absolute()
        self.save_every = int(save_every)
        self.keep = keep
        self._mgr = None
        self.restored_state: Optional[Any] = None
        if self.enabled:
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=keep, create=True, enable_async_checkpointing=True),
            )

    @property
    def enabled(self) -> bool:
        return self.save_every > 0

    def restore_step(self, state_like: Any) -> int:
        """Restore the latest checkpoint into ``restored_state``.

        ``state_like`` is a live pytree of the right structure (e.g. the
        freshly-initialized state); returns the step to resume FROM (0 when
        no checkpoint exists).
        """
        if not self.enabled:
            return 0
        import orbax.checkpoint as ocp

        latest = self._mgr.latest_step()
        if latest is None:
            return 0
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        self.restored_state = self._mgr.restore(
            latest, args=ocp.args.StandardRestore(abstract))
        logger.info("Resumed training from checkpoint step %d (%s)",
                    latest, self.directory)
        return int(latest)

    def maybe_save(self, step: int, state: Any) -> bool:
        if not self.enabled or step % self.save_every != 0:
            return False
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        return True

    def save(self, step: int, state: Any) -> None:
        if self._mgr is not None:
            import orbax.checkpoint as ocp

            self._mgr.save(step, args=ocp.args.StandardSave(state), force=True)

    def finalize(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
            self._mgr = None
