"""Training checkpoints + resume — a capability the reference lacks.

Reference gap (SURVEY.md §5.4): PredictionIO persists *final* models only;
a killed ``pio train`` restarts from scratch (Spark checkpointing inside
MLlib ALS only truncates RDD lineage).  Here mid-training resume is
first-class: orbax async sharded checkpoints every N steps, restored
automatically when a training loop starts over the same directory.

Usage::

    ckpt = TrainCheckpointer(dir, save_every=200, fingerprint=fp)
    start = ckpt.restore_step(state_like, total_steps=total)  # 0 if fresh
    state = ckpt.restored_state or state
    for step in range(start, total):
        state, loss = train_step(...)
        ckpt.maybe_save(step + 1, state)
    ckpt.complete()   # flush AND clear — a finished run leaves no
    ckpt.close()      # checkpoints behind to stale-resume the next one
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

import jax

logger = logging.getLogger(__name__)

__all__ = ["TrainCheckpointer"]


class TrainCheckpointer:
    """Thin orbax CheckpointManager wrapper for pytree train states.

    Saves are async (orbax default) — the device keeps training while the
    host serializes.  Restore uses the latest complete step.  Sharded
    ``jax.Array`` leaves round-trip with their shardings preserved when the
    same mesh is live.
    """

    def __init__(self, directory, *, save_every: int = 0, keep: int = 3,
                 fingerprint: Optional[str] = None):
        self.directory = Path(directory).absolute()
        self.save_every = int(save_every)
        self.keep = keep
        self.fingerprint = fingerprint
        self._mgr = None
        self._discarded = False  # fingerprint mismatch purged stale steps
        self.restored_state: Optional[Any] = None
        if self.enabled:
            import orbax.checkpoint as ocp

            self._check_fingerprint()
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=keep, create=True, enable_async_checkpointing=True),
            )

    @property
    def _fingerprint_path(self) -> Path:
        return self.directory / "fingerprint.txt"

    def _check_fingerprint(self) -> None:
        """Refuse checkpoints written for a different config/data.

        Resuming a train over checkpoints from a *different* run (config
        changed, new events ingested) would silently return stale or
        fast-forwarded factors.  A mismatched fingerprint purges the stale
        steps so the run starts fresh — loudly.
        """
        if self.fingerprint is None:
            return
        fp = self._fingerprint_path

        def is_step_dir(c: Path) -> bool:
            # A digit name alone is not proof: the user may keep an
            # unrelated "2024/" in the directory they pointed us at.  Real
            # orbax steps carry the metadata marker (in-flight ones don't
            # yet — those match only the orbax tmp suffix).
            return (c.is_dir() and c.name.isdigit()
                    and (c / "_CHECKPOINT_METADATA").exists())

        has_steps = self.directory.is_dir() and any(
            is_step_dir(c) for c in self.directory.iterdir())
        if fp.exists() or has_steps:
            # Steps with NO fingerprint file (dir written by an older
            # version, or by a run that didn't fingerprint) are treated as
            # a mismatch: resuming unvalidated state is the bug this guard
            # exists to stop.
            stored = fp.read_text().strip() if fp.exists() else "<absent>"
            if stored != self.fingerprint:
                logger.warning(
                    "Checkpoint dir %s was written for a different "
                    "config/data (fingerprint %s != %s); discarding stale "
                    "checkpoints and training from scratch.",
                    self.directory, stored, self.fingerprint)
                import shutil

                # Purge ONLY checkpoint artifacts (orbax step dirs are
                # numeric, in-flight saves end .orbax-checkpoint-tmp) — the
                # user may have pointed --checkpoint-dir at a directory
                # holding unrelated files.
                for child in self.directory.iterdir():
                    if is_step_dir(child) or (
                            child.is_dir() and child.name.endswith(
                                ".orbax-checkpoint-tmp")):
                        shutil.rmtree(child, ignore_errors=True)
                if fp.exists():
                    fp.unlink()
                self._discarded = True
        self.directory.mkdir(parents=True, exist_ok=True)
        fp.write_text(self.fingerprint)

    @property
    def enabled(self) -> bool:
        return self.save_every > 0

    def restore_step(self, state_like: Any,
                     total_steps: Optional[int] = None) -> int:
        """Restore the latest checkpoint into ``restored_state``.

        ``state_like`` is a live pytree of the right structure (e.g. the
        freshly-initialized state); returns the step to resume FROM (0 when
        no checkpoint exists).  Pass ``total_steps`` so a checkpoint at or
        beyond the end of the run — which means the training loop would not
        execute at all — is flagged loudly.
        """
        if not self.enabled:
            return 0
        import orbax.checkpoint as ocp

        if self._discarded:
            # The fingerprint mismatch at init is authoritative: any step
            # visible now is a stale async save from the previous run that
            # finalized AFTER the purge (its background writer was still
            # committing when the process reused this directory).
            for step in list(self._mgr.all_steps()):
                self._mgr.delete(step)
            return 0
        latest = self._mgr.latest_step()
        if latest is None:
            return 0
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        self.restored_state = self._mgr.restore(
            latest, args=ocp.args.StandardRestore(abstract))
        logger.info("Resumed training from checkpoint step %d (%s)",
                    latest, self.directory)
        if total_steps is not None and latest >= total_steps:
            logger.warning(
                "Checkpoint step %d >= total training steps %d: the "
                "training loop will not run and the checkpointed state is "
                "returned as-is.  If this is a fresh retrain, the previous "
                "run did not complete cleanly (a completed run clears its "
                "checkpoints); delete %s to train from scratch.",
                latest, total_steps, self.directory)
        return int(latest)

    def maybe_save(self, step: int, state: Any) -> bool:
        if not self.enabled or step % self.save_every != 0:
            return False
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        return True

    def save(self, step: int, state: Any) -> None:
        """Off-cadence forced save (preemption/watchdog paths).  A step
        already on disk is NOT re-saved: orbax refuses to overwrite an
        existing step directory, and the state for that step is already
        durable anyway."""
        if self._mgr is not None:
            import orbax.checkpoint as ocp

            if step in set(self._mgr.all_steps()):
                return
            self._mgr.save(step, args=ocp.args.StandardSave(state), force=True)

    def flush(self) -> None:
        """Block until every pending async save is durable on disk (the
        watchdog calls this before aborting a hung run, so the resume
        point survives the abort)."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def complete(self) -> None:
        """Mark the run finished: flush pending saves, then CLEAR them.

        A completed train persists its final model through the normal model
        store; leaving mid-train checkpoints behind would make the next
        retrain over the same directory fast-forward past its loop and
        silently return the stale factors.
        """
        if self._mgr is None:
            return
        self._mgr.wait_until_finished()
        for step in list(self._mgr.all_steps()):
            self._mgr.delete(step)
        if self.fingerprint is not None and self._fingerprint_path.exists():
            self._fingerprint_path.unlink()

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
            self._mgr = None
