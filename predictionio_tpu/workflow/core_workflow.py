"""CoreWorkflow — train/eval runs with engine-instance lifecycle.

Reference: core/.../workflow/CoreWorkflow.scala (runTrain / runEvaluation)
and CreateWorkflow.scala (the spark-submit main).  Call stack parity with
SURVEY.md §3.1/§3.4:

    run_train: bind params → EngineInstance(TRAINING) → Engine.train
      → persist models → EngineInstance(COMPLETED | FAILED)
    run_evaluation: sweep EngineParamsGenerator candidates → Engine.eval
      → Metric.calculate → EvaluationInstance(EVALCOMPLETED)

Model persistence (reference §5.4): models implementing
:class:`~predictionio_tpu.controller.PersistentModel` save themselves (e.g.
orbax sharded checkpoints); everything else is pickled into the MODELDATA
blob store keyed by engine-instance id.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import pickle
import traceback
from typing import Any, List, Optional, Sequence, Tuple

from predictionio_tpu.controller import (
    Engine,
    EngineParams,
    EngineVariant,
    Evaluation,
    EngineParamsGenerator,
    MetricEvaluatorResult,
    PersistentModel,
    RuntimeContext,
    WarmStartFallback,
)
from predictionio_tpu.controller.params import params_to_dict
from predictionio_tpu.data.storage import (
    EngineInstance,
    EvaluationInstance,
    Model,
    Storage,
)
from predictionio_tpu.obs import (
    get_memory_sampler,
    phase as obs_phase,
    publish_event,
    trace as obs_trace,
)
from predictionio_tpu.resilience.supervision import TrainPreempted
from predictionio_tpu.version import __version__

logger = logging.getLogger(__name__)

__all__ = ["WorkflowError", "run_train", "load_models", "run_evaluation",
           "data_watermark", "DATA_WATERMARK_KEY"]

# EngineInstance.env keys of the online-refresh loop (ISSUE 10).  The env
# dict rides every backend's existing row format (JSON column / deepcopy /
# RPC), so the watermark needs no storage schema change.
DATA_WATERMARK_KEY = "dataWatermark"   # ISO-8601 until-bound of the data read
REFRESH_MODE_KEY = "refreshMode"       # "full" | "warm"
WARM_FROM_KEY = "warmStartFrom"        # parent COMPLETED instance id


def data_watermark(instance: EngineInstance) -> Optional[_dt.datetime]:
    """The data high-watermark recorded on a train run: every event with
    ``event_time < watermark`` was visible to (and bounded the read of)
    that generation.  None for instances written before ISSUE 10."""
    raw = (instance.env or {}).get(DATA_WATERMARK_KEY)
    if not raw:
        return None
    try:
        return _dt.datetime.fromisoformat(raw)
    except ValueError:
        return None


class WorkflowError(RuntimeError):
    pass


def _now() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def _class_path(obj: Any) -> str:
    cls = obj if isinstance(obj, type) else type(obj)
    return f"{cls.__module__}:{cls.__qualname__}"


def _engine_params_json(engine_params: EngineParams) -> dict:
    return engine_params.to_json_dict()


def run_train(
    engine: Engine,
    variant: EngineVariant,
    ctx: Optional[RuntimeContext] = None,
    *,
    engine_id: Optional[str] = None,
    engine_version: str = __version__,
    warm_from: Any = None,
) -> str:
    """Train an engine variant; returns the COMPLETED engine-instance id.

    Reference: CoreWorkflow.runTrain — including the FAILED-status write on
    error (§5.3 failure observation) which the caller relies on.

    Every run stamps a **data watermark** BEFORE the datasource reads and
    scopes the read to ``event_time < watermark`` (via
    :class:`~predictionio_tpu.data.store.WindowedEventStore`), recording
    the bound in ``instance.env[dataWatermark]`` — this is what makes
    consecutive refresh windows gap- and overlap-free (ISSUE 10): events
    landing mid-read belong to the NEXT generation, by construction.

    ``warm_from`` (a :class:`~predictionio_tpu.refresh.WarmStartContext`)
    switches the run to delta warm-start mode: the datasource reads only
    ``[previous watermark, new watermark)`` and each algorithm continues
    the previous generation's model.  Any
    :class:`~predictionio_tpu.controller.WarmStartFallback` (unsupported
    algorithm, oversized delta, regressed continuation) falls back to a
    full retrain over the complete window inside the SAME engine
    instance — a refresh cycle always lands one generation.
    """
    ctx = ctx or RuntimeContext.create()
    storage: Storage = ctx.storage
    engine_params = engine.bind_engine_params(variant.raw)
    ep_json = _engine_params_json(engine_params)
    # The watermark is pinned before ANY event is read; naive-free UTC ISO
    # so every backend and every host parses the same instant back.
    watermark = _now()
    env = {DATA_WATERMARK_KEY: watermark.isoformat(),
           REFRESH_MODE_KEY: "warm" if warm_from is not None else "full"}
    if warm_from is not None and getattr(warm_from, "instance", None):
        env[WARM_FROM_KEY] = warm_from.instance.id
    instance = EngineInstance(
        id=None,
        status="TRAINING",
        start_time=_now(),
        end_time=None,
        engine_id=engine_id or variant.engine_factory,
        engine_version=engine_version,
        engine_variant=variant.variant_id,
        engine_factory=variant.engine_factory,
        env=env,
        datasource_params=json.dumps(ep_json["datasource"]["params"]),
        preparator_params=json.dumps(ep_json["preparator"]["params"]),
        algorithms_params=json.dumps(ep_json["algorithms"]),
        serving_params=json.dumps(ep_json["serving"]["params"]),
    )
    instances = storage.get_engine_instances()
    instance_id = instances.insert(instance)
    logger.info("EngineInstance %s TRAINING (factory=%s)", instance_id, variant.engine_factory)
    # Per-train-run device-memory peak (obs.runtime): fresh peak window
    # at run start, the poll thread tracks the high-water mark, and the
    # final sample under the trace pins pio_device_mem_peak_bytes to THIS
    # run — surfaced by `pio status --metrics-url`.
    sampler = get_memory_sampler()
    sampler.reset_peak()
    sampler.start()

    def _windowed(start: Optional[_dt.datetime]) -> RuntimeContext:
        from predictionio_tpu.data.store import WindowedEventStore

        return dataclasses.replace(
            ctx, event_store=WindowedEventStore(storage, start, watermark))

    try:
        # One trace per training run: the DASE phases inside Engine.train
        # (datasource/prepare/algorithm) plus the persist phase below hang
        # off this root; recorded to the ring / PIO_TRACE_FILE on exit.
        with obs_trace("workflow.train",
                       engine_factory=variant.engine_factory,
                       instance=instance_id,
                       mode=env[REFRESH_MODE_KEY]):
            models = None
            if warm_from is not None:
                try:
                    wctx = _windowed(warm_from.start_time)
                    models = _maybe_profiled(
                        ctx, lambda: engine.train(wctx, engine_params,
                                                  warm=warm_from))
                except WarmStartFallback as e:
                    # The fallback is part of the contract, not a failure:
                    # retrain fully over the complete window, same
                    # instance, and record which road was taken.
                    logger.warning(
                        "EngineInstance %s: warm-start declined (%s) — "
                        "falling back to a full retrain", instance_id,
                        e.reason)
                    publish_event("refresh.warm_fallback",
                                  instance=instance_id,
                                  reason=e.reason[:200])
                    instance.env[REFRESH_MODE_KEY] = "full_fallback"
            if models is None:
                fctx = _windowed(None)
                models = _maybe_profiled(
                    ctx, lambda: engine.train(fctx, engine_params))
            with obs_phase("train.persist"):
                _persist_models(models, instance_id, ctx)
            sampler.sample_once()
        instance.status = "COMPLETED"
        instance.end_time = _now()
        instances.update(instance)
        logger.info(
            "EngineInstance %s COMPLETED in %.1fs",
            instance_id,
            (instance.end_time - instance.start_time).total_seconds(),
        )
        return instance_id
    except TrainPreempted as e:
        # SIGTERM preemption (resilience/supervision.py): a final
        # checkpoint was written, so the distinct status tells the
        # dashboard/supervisor this run resumes, not failed.
        instance.status = "PREEMPTED"
        instance.end_time = _now()
        instances.update(instance)
        logger.warning("EngineInstance %s PREEMPTED at step %d "
                       "(rerun resumes from the checkpoint)",
                       instance_id, e.step)
        raise
    except BaseException:
        # BaseException, not Exception: the step watchdog's abort raises
        # KeyboardInterrupt (interrupt_main) — that run must land as
        # FAILED, not sit in TRAINING forever as a phantom live train.
        instance.status = "FAILED"
        instance.end_time = _now()
        instances.update(instance)
        logger.error("EngineInstance %s FAILED:\n%s", instance_id, traceback.format_exc())
        raise


def _maybe_profiled(ctx: RuntimeContext, fn):
    """JAX profiler integration (SURVEY.md §5.1 rebuild note): set
    ``PIO_PROFILE_DIR`` (or workflow param ``profile_dir``) to capture an
    xplane trace of the training run, viewable in TensorBoard/XProf —
    the substrate's answer to the reference's Spark UI stage timings."""
    import os

    trace_dir = ctx.workflow_params.get("profile_dir") or os.environ.get(
        "PIO_PROFILE_DIR")
    if not trace_dir:
        return fn()
    import jax

    logger.info("Capturing JAX profiler trace to %s", trace_dir)
    with jax.profiler.trace(str(trace_dir)):
        return fn()


def _persist_models(models: Sequence[Any], instance_id: str, ctx: RuntimeContext) -> None:
    """One manifest blob per instance; each entry pickled or self-persisted."""
    entries: List[dict] = []
    payloads: List[Optional[bytes]] = []
    for i, model in enumerate(models):
        if isinstance(model, PersistentModel):
            saved = model.save(f"{instance_id}.{i}", ctx)
            if saved:
                entries.append({"kind": "persistent", "class": _class_path(model)})
                payloads.append(None)
                continue
        entries.append({"kind": "pickle", "class": _class_path(model)})
        payloads.append(pickle.dumps(model, protocol=pickle.HIGHEST_PROTOCOL))
    blob = pickle.dumps({"entries": entries, "payloads": payloads},
                        protocol=pickle.HIGHEST_PROTOCOL)
    ctx.storage.get_models().insert(Model(id=instance_id, models=blob))


def load_models(
    engine: Engine,
    instance: EngineInstance,
    ctx: Optional[RuntimeContext] = None,
) -> List[Any]:
    """Load the trained models of a COMPLETED instance (reference:
    CreateServer model loading / PersistentModelLoader)."""
    ctx = ctx or RuntimeContext.create()
    blob = ctx.storage.get_models().get(instance.id)
    if blob is None:
        raise WorkflowError(f"No model data for engine instance {instance.id}.")
    manifest = pickle.loads(blob.models)
    engine_params = _bind_instance_params(engine, instance)
    algo_params = dict(engine_params.algorithms_params)
    models: List[Any] = []
    for i, (entry, payload) in enumerate(zip(manifest["entries"], manifest["payloads"])):
        if entry["kind"] == "pickle":
            models.append(pickle.loads(payload))
        else:
            mod_name, _, qual = entry["class"].partition(":")
            import importlib

            cls = importlib.import_module(mod_name)
            for part in qual.split("."):
                cls = getattr(cls, part)
            name_i = list(algo_params)[i] if i < len(algo_params) else None
            models.append(cls.load(f"{instance.id}.{i}", algo_params.get(name_i), ctx))
    # Post-load re-parallelization hook (reference: SURVEY §3.2 — "P
    # models may re-parallelize" in CreateServer): a model that wants a
    # serving-time device layout (e.g. a corpus too large for one chip,
    # re-sharded over ctx.mesh) reshapes itself here.
    for m in models:
        hook = getattr(m, "post_load", None)
        if callable(hook):
            try:
                hook(ctx)
            except Exception:
                logger.exception("model post_load hook failed; serving "
                                 "continues with the loaded layout")
    return models


def _bind_instance_params(engine: Engine, instance: EngineInstance) -> EngineParams:
    """Rebind the params snapshot stored on the instance row."""
    variant_like = {
        "datasource": {"params": json.loads(instance.datasource_params)},
        "preparator": {"params": json.loads(instance.preparator_params)},
        "algorithms": json.loads(instance.algorithms_params),
        "serving": {"params": json.loads(instance.serving_params)},
    }
    return engine.bind_engine_params(variant_like)


def instance_engine_params(engine: Engine, instance: EngineInstance) -> EngineParams:
    """Public alias used by the serving layer."""
    return _bind_instance_params(engine, instance)


def run_evaluation(
    evaluation: Evaluation,
    params_generator: EngineParamsGenerator,
    ctx: Optional[RuntimeContext] = None,
    *,
    evaluation_class: str = "",
    params_generator_class: str = "",
    checkpoint_dir: Optional[str] = None,
) -> Tuple[str, MetricEvaluatorResult]:
    """Sweep engine-params candidates and score them (reference:
    CoreWorkflow.runEvaluation + MetricEvaluator.evaluateBase, §3.4).

    ``checkpoint_dir`` (ISSUE 15 satellite; default
    ``PIO_EVAL_CHECKPOINT_DIR``) makes the sweep preemption-safe: each
    completed (candidate, fold) unit persists as it finishes, a SIGTERM
    mid-sweep marks the instance EVALPREEMPTED and propagates
    ``TrainPreempted`` (the CLI exits 143, same contract as training),
    and rerunning the same command resumes from the completed units —
    which are cleared once the sweep lands."""
    from predictionio_tpu.controller.engine import EvalCheckpoint

    ctx = ctx or RuntimeContext.create()
    storage: Storage = ctx.storage
    ck_dir = checkpoint_dir or os.environ.get("PIO_EVAL_CHECKPOINT_DIR")
    checkpoint = EvalCheckpoint(ck_dir) if ck_dir else None
    instance = EvaluationInstance(
        id=None,
        status="EVALRUNNING",
        start_time=_now(),
        end_time=None,
        evaluation_class=evaluation_class or _class_path(evaluation.engine),
        engine_params_generator_class=params_generator_class or _class_path(params_generator),
    )
    instances = storage.get_evaluation_instances()
    instance_id = instances.insert(instance)
    try:
        engine = evaluation.engine
        candidates = list(params_generator.engine_params_list)
        if not candidates:
            raise WorkflowError("EngineParamsGenerator produced no candidates.")
        if checkpoint is not None and checkpoint.completed():
            logger.info("eval sweep resuming: %d completed "
                        "(candidate, fold) unit(s) found in %s",
                        checkpoint.completed(), ck_dir)
        scored: List[Tuple[EngineParams, float, List[float]]] = []
        # Shared-prep sweep: folds are read + prepared once per distinct
        # datasource/preparator config, not once per candidate.
        all_eval_data = engine.eval_multi(ctx, candidates,
                                          checkpoint=checkpoint)
        for i, (engine_params, eval_data) in enumerate(
                zip(candidates, all_eval_data)):
            score = evaluation.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in evaluation.other_metrics]
            scored.append((engine_params, score, others))
            logger.info("eval candidate %d/%d: %s=%s", i + 1, len(candidates),
                        evaluation.metric.header, score)
        best_index = max(
            range(len(scored)),
            key=lambda i: (scored[i][1],),
        )
        result = MetricEvaluatorResult(
            best_score=scored[best_index][1],
            best_engine_params=scored[best_index][0],
            best_index=best_index,
            metric_header=evaluation.metric.header,
            other_metric_headers=[m.header for m in evaluation.other_metrics],
            candidate_scores=scored,
        )
        instance.status = "EVALCOMPLETED"
        instance.end_time = _now()
        instance.evaluator_results = result.summary()
        instance.evaluator_results_json = json.dumps(
            {
                "bestScore": result.best_score,
                "bestIndex": result.best_index,
                "metric": result.metric_header,
                "bestEngineParams": result.best_engine_params.to_json_dict(),
                "candidates": [
                    {"engineParams": p.to_json_dict(), "score": s, "others": o}
                    for p, s, o in scored
                ],
            }
        )
        instances.update(instance)
        if checkpoint is not None:
            checkpoint.clear()  # landed: a rerun is a fresh sweep
        return instance_id, result
    except TrainPreempted:
        # SIGTERM mid-sweep: the completed units are on disk and the CLI
        # owns the exit code — not a failed evaluation.
        instance.status = "EVALPREEMPTED"
        instance.end_time = _now()
        instances.update(instance)
        raise
    except Exception:
        instance.status = "EVALFAILED"
        instance.end_time = _now()
        instances.update(instance)
        raise
