"""Workflow orchestration (reference: core/.../workflow/ — SURVEY.md §2.1)."""

from predictionio_tpu.workflow.core_workflow import (
    WorkflowError,
    load_models,
    run_evaluation,
    run_train,
)

__all__ = ["WorkflowError", "load_models", "run_evaluation", "run_train"]
